"""paddle.v2.attr-compatible attribute classes.

Reference: python/paddle/trainer_config_helpers/attrs.py —
ParameterAttribute (Param), ExtraLayerAttribute (Extra). The heavy lifting
lives in core/registry.ParamAttr; these are thin API-parity wrappers.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.core.registry import ParamAttr


def Param(name: Optional[str] = None, learning_rate: float = 1.0,
          l1_rate: Optional[float] = None, l2_rate: Optional[float] = None,
          initial_std: Optional[float] = None, initial_mean: float = 0.0,
          is_static: bool = False, sparse_update: bool = False,
          gradient_clipping_threshold: Optional[float] = None,
          initializer=None, **kwargs) -> ParamAttr:
    return ParamAttr(name=name, learning_rate=learning_rate,
                     l1_rate=l1_rate, l2_rate=l2_rate,
                     initial_std=initial_std, initial_mean=initial_mean,
                     is_static=is_static, sparse=sparse_update,
                     gradient_clipping_threshold=gradient_clipping_threshold,
                     initializer=initializer)


ParameterAttribute = Param


class ExtraLayerAttribute:
    """Extra layer attrs: drop_rate and error clipping.

    Reference attrs.py ExtraLayerAttribute(drop_rate=, device=,
    error_clipping_threshold=). `device` pinning is obsolete under XLA
    (GSPMD shards instead); accepted and ignored.
    """

    def __init__(self, drop_rate: Optional[float] = None,
                 device: Optional[int] = None,
                 error_clipping_threshold: Optional[float] = None):
        self.drop_rate = drop_rate
        self.device = device
        self.error_clipping_threshold = error_clipping_threshold


Extra = ExtraLayerAttribute
ExtraAttr = ExtraLayerAttribute
