"""paddle.v2.attr-compatible attribute classes.

Reference: python/paddle/trainer_config_helpers/attrs.py —
ParameterAttribute (Param), ExtraLayerAttribute (Extra). The heavy lifting
lives in core/registry.ParamAttr; these are thin API-parity wrappers.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.core.registry import ParamAttr


class HookAttribute:
    """Parameter update hook (attrs.py:59 HookAttribute → C++
    ParameterUpdaterHook.cpp). Supported: type='pruning' with
    sparsity_ratio — a static mask from the initial weight magnitudes
    applied after every update (StaticPruningHook)."""

    def __init__(self, type: str, sparsity_ratio: Optional[float] = None):
        assert type in ("pruning",), f"unsupported hook type {type!r}"
        self.type = type
        self.sparsity_ratio = 0.6 if sparsity_ratio is None else \
            float(sparsity_ratio)
        if self.type == "pruning":
            assert 0.0 <= self.sparsity_ratio <= 1.0


def Param(name: Optional[str] = None, learning_rate: float = 1.0,
          l1_rate: Optional[float] = None, l2_rate: Optional[float] = None,
          initial_std: Optional[float] = None, initial_mean: float = 0.0,
          is_static: bool = False, sparse_update: bool = False,
          gradient_clipping_threshold: Optional[float] = None,
          initializer=None, update_hooks=None, **kwargs) -> ParamAttr:
    return ParamAttr(name=name, learning_rate=learning_rate,
                     l1_rate=l1_rate, l2_rate=l2_rate,
                     initial_std=initial_std, initial_mean=initial_mean,
                     is_static=is_static, sparse=sparse_update,
                     gradient_clipping_threshold=gradient_clipping_threshold,
                     initializer=initializer, update_hooks=update_hooks)


ParameterAttribute = Param


class ExtraLayerAttribute:
    """Extra layer attrs: drop_rate and error clipping.

    Reference attrs.py ExtraLayerAttribute(drop_rate=, device=,
    error_clipping_threshold=). `device` pinning is obsolete under XLA
    (GSPMD shards instead); accepted and ignored.
    """

    def __init__(self, drop_rate: Optional[float] = None,
                 device: Optional[int] = None,
                 error_clipping_threshold: Optional[float] = None):
        self.drop_rate = drop_rate
        self.device = device
        self.error_clipping_threshold = error_clipping_threshold


Extra = ExtraLayerAttribute
ExtraAttr = ExtraLayerAttribute
