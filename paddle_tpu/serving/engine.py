"""Continuous-batching decode engine over a paged KV cache.

The dense-cache decoder (models/decode.py) serves generation the way
2017 served everything: per-request-batch cache allocation, whole-batch
lockstep, one compilation per shape. Under ragged production traffic
that wastes the chip twice — short sequences pad to the longest, and a
finished sequence's slot idles until the whole batch drains. This
module is the production loop those papers (Orca's iteration-level
scheduling, PagedAttention's block-pooled KV) built for serving LLMs:

- ``PagePool``: a host-side REFCOUNTED free-list over preallocated
  device page pools ([L, n_pages, page_size, g, dh] —
  models/decode.PagedDecoder). KV memory is pooled across ALL requests
  in fixed-size pages, so admission is a pages-free check, not a
  worst-case-length reservation. A page may be owned by several slots
  AND the prefix trie at once; it returns to the free list only at
  refcount zero.
- ``DecodeEngine``: a persistent decode loop over a FIXED slot batch.
  Each iteration feeds every active slot a WINDOW of up to W tokens
  (prompt tokens teacher-forced first — prefill interleaves with other
  slots' decoding, no whole-batch barrier), dispatches ONE jitted step,
  and does host-side bookkeeping: requests join free slots mid-flight,
  finished/cancelled/expired requests free their pages immediately, and
  page-pool exhaustion first reclaims cold prefix-cache pages, then
  PREEMPTS the youngest request (pages back to the pool, request
  re-queued; greedy decode replays prompt + generated tokens, so its
  final output is unchanged). Joins/evictions only edit small int32
  inputs — the step never recompiles.
- Admission control by FREE KV PAGES: a request that could never fit
  the pool is rejected outright (``kv_capacity``); the queue head only
  takes a slot when enough NOVEL pages are free to reach its first new
  token (shared-prefix pages are free to attach); the wait queue
  itself is bounded (``queue_full``).

Round 9 stacks the three decode-speed multipliers on that loop:

- **Shared-prefix KV reuse** (serving/prefix.py): finished/evicted
  slots leave their complete pages in a radix index; a new request
  whose prompt walks the same token path attaches those pages instead
  of recomputing them — admission charges only novel pages, warm-
  prefix TTFT drops the whole shared prefill, and divergence inside a
  page is copy-on-write via ``PagedDecoder.copy_page``.
- **Speculative decoding** (models/decode.DraftDecoder): a small draft
  proposes up to k tokens per slot; the target VERIFIES them in the
  same [S, W] jitted step it uses for prefill (W = spec_k + 1 fixed at
  construction — zero new compiles under churn). Greedy token-identity
  is the acceptance rule, so output is token-exact vs. the dense
  baseline; rejected rows are dead weight the kv_len mask never reads
  and the next feed overwrites.
- **Allocated-pages attention** (ops/pallas_decode.py): the paged step
  walks only each slot's allocated pages on the TPU kernel path,
  cutting cache reads from ``max_seq_len`` to true ragged lengths.

``stats()`` exports KV-page occupancy, slot utilization, per-token
latency percentiles, prefix-hit and speculation accounting and the
scheduling counters; serving/http.py re-exports them as Prometheus
gauges on GET /metrics, alongside the module-level
``paddle_tpu_prefix_*`` / ``paddle_tpu_spec_*`` families registered
here. Faults for the chaos suite (mid-decode join/evict/cancel, CoW
churn, cancel-mid-verify) drive the ``_step_interceptor`` seam — see
testing/faults.py (j)+(n) and tests/test_serving_faults.py.
docs/perf.md ("Continuous batching", "Prefix reuse + speculative
decoding") has the measured before/after; docs/robustness.md the
fault families.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from paddle_tpu.obs import context as obs_context
from paddle_tpu.analysis.lockdep import named_condition, named_lock
from paddle_tpu.obs.events import emit as journal_emit
from paddle_tpu.obs.flight import FLIGHT
from paddle_tpu.obs.metrics import REGISTRY as _METRICS
from paddle_tpu.obs.profile import PROFILER
from paddle_tpu.serving.prefix import PrefixIndex
from paddle_tpu.serving.server import (Expired, Rejected, ServerClosed,
                                       ServingError)
from paddle_tpu.serving.spill import SpillEntry, SpillStore
from paddle_tpu.utils.stats import global_counters, stat_timer

# /metrics families for the round-9 multipliers (idempotent: the
# registry returns the existing family on re-registration)
_PREFIX_HIT = _METRICS.counter(
    "paddle_tpu_prefix_hit_pages",
    "KV pages attached from the shared-prefix index instead of "
    "recomputed")
_PREFIX_MISS = _METRICS.counter(
    "paddle_tpu_prefix_miss_pages",
    "prompt pages admitted with no shared-prefix match")
_PREFIX_COW = _METRICS.counter(
    "paddle_tpu_prefix_cow_copies",
    "copy-on-write page copies on intra-page prefix divergence")
_PREFIX_SHARED = _METRICS.gauge(
    "paddle_tpu_prefix_shared_pages",
    "physical pages currently referenced by more than one owner")
_SPEC_PROPOSED = _METRICS.counter(
    "paddle_tpu_spec_proposed_tokens_total",
    "draft-model tokens proposed for target verification")
_SPEC_ACCEPTED = _METRICS.counter(
    "paddle_tpu_spec_accepted_tokens_total",
    "draft proposals the target model accepted (greedy token match)")
# the two-tier KV plane (int8 pages + host spill — docs/robustness.md
# "Two-tier KV cache")
_SPILL_PAGES = _METRICS.counter(
    "paddle_tpu_kv_pages_spilled_total",
    "cold prefix-cache pages spilled device->host instead of freed")
_SPILL_RESTORED = _METRICS.counter(
    "paddle_tpu_kv_pages_restored_total",
    "spilled pages restored host->device on a prefix match, before "
    "prefill was charged")
_SPILL_INTEGRITY = _METRICS.counter(
    "paddle_tpu_kv_spill_integrity_drops_total",
    "spill entries dropped on checksum mismatch or transfer failure "
    "— a torn page degrades to a prefix miss, never a restore")
_SPILLED_NOW = _METRICS.gauge(
    "paddle_tpu_kv_pages_spilled_now",
    "pages currently resident in the host-RAM spill tier")


class PagePool:
    """Host-side refcounted allocator over the device page pools.

    Physical page 0 is RESERVED as the null page (inactive slots write
    there; unassigned page-table entries point there) and is never
    handed out. ``alloc()`` hands a page out at refcount 1; the prefix
    trie and additional slots take further refs with ``ref()``;
    ``free()`` decrements and only returns the page to the free list
    at zero. Freeing a page that holds no refs raises — refcount
    UNDERFLOWS are as loud as double frees, and the chaos suite
    asserts ``leaked == 0`` after every fault storm."""

    def __init__(self, num_pages: int):
        assert num_pages >= 2, num_pages
        self.num_pages = int(num_pages)
        self.usable = self.num_pages - 1
        self._lock = named_lock("serving.pagepool")
        # pop() hands out page 1 first — deterministic layouts in tests
        self._free_list = list(range(self.num_pages - 1, 0, -1))
        self._allocated: set = set()     # ptlint: guarded-by(serving.pagepool)
        self._refs: Dict[int, int] = {}  # ptlint: guarded-by(serving.pagepool)
        self.high_water = 0

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free_list)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return len(self._allocated)

    @property
    def shared_pages(self) -> int:
        with self._lock:
            return sum(1 for c in self._refs.values() if c > 1)

    def alloc(self) -> Optional[int]:
        with self._lock:
            if not self._free_list:
                return None
            p = self._free_list.pop()
            self._allocated.add(p)
            self._refs[p] = 1
            self.high_water = max(self.high_water, len(self._allocated))
            return p

    def ref(self, page: int) -> None:
        """Take one more reference on an allocated page (a slot
        attaching a shared prefix page, the trie indexing a slot's
        page, a CoW-source pin)."""
        with self._lock:
            if page not in self._allocated:
                raise ValueError(
                    f"page {page} ref'd but not allocated — the "
                    "refcount plumbing lost track of it")
            self._refs[page] += 1

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def refcount_histogram(self) -> Dict[int, int]:
        """{refcount: page count} over allocated pages — the flight
        bundle's sharing picture."""
        with self._lock:
            hist: Dict[int, int] = {}
            for c in self._refs.values():
                hist[c] = hist.get(c, 0) + 1
            return hist

    def free(self, pages) -> None:
        with self._lock:
            for p in pages:
                if p not in self._allocated:
                    raise ValueError(
                        f"page {p} returned to the pool but not "
                        "allocated — double free, refcount underflow "
                        "or foreign page id")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._allocated.discard(p)
                    self._free_list.append(p)

    def accounting(self) -> dict:
        with self._lock:
            return {"total_usable": self.usable,
                    "free": len(self._free_list),
                    "allocated": len(self._allocated),
                    "leaked": self.usable - len(self._free_list)
                    - len(self._allocated),
                    "refs_total": sum(self._refs.values()),
                    "shared": sum(1 for c in self._refs.values()
                                  if c > 1),
                    "high_water": self.high_water}


class GenRequest:
    """Future-like handle for one generation request.

    ``get()`` blocks for completion and returns the generated token ids
    (including the eos token when one stopped it). A CANCELLED request
    (client disconnect) settles with the tokens generated so far — the
    stream semantics. Deadline expiry / server shutdown settle with the
    typed serving errors. ``cancel()`` is safe from any thread at any
    time; the engine observes it at the next iteration and returns the
    request's pages to the pool. ``prefix_hit_pages`` /
    ``accepted_tokens`` carry the round-9 per-request accounting into
    the /generate response (serving/http.py)."""

    def __init__(self, prompt, max_new_tokens: int,
                 eos_id: Optional[int], deadline: Optional[float],
                 now: float, trace_id: Optional[str] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline = deadline          # absolute time.monotonic()
        # the request's end-to-end correlation id: every flight/journal
        # record this request touches carries it
        self.trace_id = trace_id or obs_context.new_trace_id()
        self.tokens: List[int] = []
        self.state = "waiting"  # waiting|running|done|cancelled|failed
        self.error: Optional[ServingError] = None
        self.done = threading.Event()
        self.submitted_at = now
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.evictions = 0
        self.prefix_hit_pages = 0
        self.accepted_tokens = 0
        self._cancelled = False

    @property
    def num_generated(self) -> int:
        return len(self.tokens)

    def cancel(self) -> None:
        self._cancelled = True

    def get(self, timeout: Optional[float] = None) -> List[int]:
        if timeout is None and self.deadline is not None:
            timeout = max(self.deadline - time.monotonic(), 0.0) + 0.25
        if not self.done.wait(timeout):
            raise Expired("generation still in flight past its "
                          "deadline/timeout")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class _Slot:
    """Host bookkeeping for one occupied decode slot."""

    __slots__ = ("req", "replay", "pos", "pages", "arrival",
                 "last_tok", "last_token_t", "draft_pos")

    def __init__(self, req: GenRequest, arrival: int):
        self.req = req
        # prompt + already-generated tokens: teacher-forced back through
        # the step on (re-)admission, so an evicted request's greedy
        # continuation is exactly what it would have produced unevicted
        self.replay = req.prompt + req.tokens
        self.pos = 0                     # next position to feed
        self.pages: List[int] = []
        self.arrival = arrival
        self.last_tok = 0
        self.last_token_t: Optional[float] = None
        # committed tokens already teacher-forced through the DRAFT
        # cache lane (speculative decoding); rolled back past rejected
        # proposals every verify
        self.draft_pos = 0

    def next_input(self) -> int:
        if self.pos < len(self.replay):
            return self.replay[self.pos]
        return self.last_tok


class DecodeEngine:
    """Persistent continuous-batching decode loop (see module doc).

    ``decoder`` is a models.TransformerDecoder (the dense reference
    path); the engine builds its PagedDecoder twin over the same
    parameter table. ``num_pages`` defaults to full capacity (every
    slot can reach ``max_seq_len``) — size it SMALLER to serve more
    slots than worst-case memory would allow and let preemption absorb
    the tail. ``draft``/``spec_k`` turn on speculative decoding (a
    second, smaller TransformerDecoder proposing ``spec_k`` tokens per
    step — greedy only); ``prefix_cache`` toggles shared-prefix KV
    reuse. Construction is cheap; the single XLA compile per jitted
    function happens on first use.

    Drive it synchronously (``step()`` / ``run()`` — deterministic, the
    test/bench mode) or as a background thread (``start()`` /
    ``shutdown()`` — the serving mode; InferenceServer wires this)."""

    def __init__(self, decoder, *, num_slots: int = 4,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 max_waiting: int = 64,
                 temperature: Optional[float] = None,
                 latency_window: int = 2048,
                 clock: Callable[[], float] = time.monotonic,
                 draft=None, spec_k: int = 0,
                 prefix_cache: bool = True,
                 attention: str = "auto",
                 warm_start: bool = True,
                 kv_quant: Optional[str] = None,
                 kv_spill_pages: int = 0):
        pos_rows = decoder.p[f"_{decoder.name}_pos_emb.w0"].shape[0]
        if max_seq_len is None:
            max_seq_len = pos_rows
        self.max_seq_len = min(int(max_seq_len), pos_rows)
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        self.spec_k = max(int(spec_k), 0) if draft is not None else 0
        if self.spec_k and temperature is not None:
            raise ValueError(
                "speculative decoding is greedy-only: the acceptance "
                "rule is token identity, which sampling breaks")
        # W = spec_k + 1: one pending token + k proposals per dispatch.
        # Fixed at construction so churn never changes the jitted shape.
        self.window = 1 + self.spec_k
        pages_per_slot = -(-self.max_seq_len // self.page_size)
        if num_pages is None:
            num_pages = self.num_slots * pages_per_slot + 1
        self.warm_start = bool(warm_start)
        self.kv_quant = kv_quant
        self.paged = decoder.paged(
            num_slots=self.num_slots, page_size=self.page_size,
            num_pages=int(num_pages),
            max_pages_per_slot=pages_per_slot, temperature=temperature,
            window=self.window, attention=attention,
            warm_start=self.warm_start, kv_quant=kv_quant)
        if kv_quant is not None and not self.paged.use_kernel:
            # int8 pages without the dequant-fused kernel: attention
            # reads through the dequantizing gather (exact einsum) —
            # correct, just full-table-width traffic. Journaled once at
            # construction so a fleet-wide scrape can spot replicas
            # paying the fallback.
            journal_emit("engine", "dequant_fallback",
                         reason="kernel_unsupported", kv_quant=kv_quant)
        self.pool = PagePool(int(num_pages))
        self.k_pool, self.v_pool = self.paged.init_pools()
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(self.pool, self.page_size) if prefix_cache
            else None)
        if kv_spill_pages and not prefix_cache:
            raise ValueError(
                "kv_spill_pages needs the prefix cache: spilled pages "
                "are keyed and restored by their trie token path")
        self.spill: Optional[SpillStore] = (
            SpillStore(int(kv_spill_pages)) if kv_spill_pages else None)
        # chaos seam (testing/faults.py family (s)): called at the
        # "read" and "commit" stages of every spill — kill_during_spill
        # raises WorkerCrash here to prove the ordering contract
        self._spill_interceptor: Optional[
            Callable[[str, tuple, int], None]] = None
        self.draft = None
        if draft is not None and self.spec_k > 0:
            from paddle_tpu.models.decode import DraftDecoder
            self.draft = DraftDecoder(
                draft, num_slots=self.num_slots,
                max_seq_len=self.max_seq_len, window=self.window,
                warm_start=self.warm_start)
            self._draft_kc, self._draft_vc = self.draft.init_caches()
        self.max_waiting = int(max_waiting)
        self.temperature = temperature
        self._clock = clock
        S, P, W = self.num_slots, pages_per_slot, self.window
        self.slots: List[Optional[_Slot]] = [None] * S
        self._tokens = np.zeros((S, W), np.int32)
        self._positions = np.zeros((S, W), np.int32)
        self._tables = np.zeros((S, P), np.int32)
        self._active = np.zeros((S, W), np.bool_)
        self._waiting: deque = deque()  # ptlint: guarded-by(serving.engine)
        self._cv = named_condition("serving.engine")
        self._accepting = True
        self._stopping = False
        self._close_now = False
        self._thread: Optional[threading.Thread] = None
        self._step_interceptor: Optional[Callable[[int], None]] = None
        self._steps = 0
        self._arrival_seq = 0
        self._active_steps_sum = 0
        self._cache_tokens_read = 0
        self._lat: deque = deque(maxlen=int(latency_window))
        self._ttft: deque = deque(maxlen=256)
        self._counters = {"submitted": 0, "finished": 0, "cancelled": 0,
                          "expired": 0, "preemptions": 0,
                          "rejected_queue": 0, "rejected_capacity": 0,
                          "closed": 0, "step_failures": 0,
                          "tokens_out": 0, "prefill_tokens": 0,
                          "prefix_hit_pages": 0, "prefix_miss_pages": 0,
                          "prefix_cow_copies": 0,
                          "prefix_evicted_pages": 0,
                          "spec_proposed_tokens": 0,
                          "spec_accepted_tokens": 0,
                          "draft_failures": 0,
                          "kv_pages_spilled": 0,
                          "kv_pages_restored": 0,
                          "kv_spill_integrity_drops": 0,
                          "kv_spill_cleared": 0}
        import jax
        self._key0 = jax.random.PRNGKey(0)
        # live-state provider for postmortem bundles: the slot table
        # and wait queue by trace_id at dump time. Weakref'd so dead
        # engines never pin themselves in the recorder.
        import weakref
        ref = weakref.ref(self)

        def _flight_state():
            eng = ref()
            if eng is None:
                return None
            slots = [
                None if sl is None else
                {"trace_id": sl.req.trace_id, "pos": sl.pos,
                 "generated": sl.req.num_generated,
                 "pages": len(sl.pages)}
                for sl in list(eng.slots)]
            with eng._cv:
                waiting = [r.trace_id for r in eng._waiting]
                steps = eng._steps
            # prefix summary AFTER _cv release: lock order is
            # engine -> prefix -> pagepool, never held together here
            prefix = eng.prefix.summary() \
                if eng.prefix is not None else None
            return {"slots": slots, "waiting_trace_ids": waiting,
                    "steps": steps,
                    "pages": eng.pool.accounting(),
                    "prefix": prefix}

        FLIGHT.register_state_provider(f"engine-{id(self):x}",
                                       _flight_state)

        # performance plane (obs/profile.py + obs/slo.py): page-pool
        # occupancy rides the off-thread memory sampler, and stats()
        # (with a derived tokens_per_s) feeds the watchdog's
        # declarative objectives. Same weakref discipline as above.
        def _pool_accounting():
            eng = ref()
            return None if eng is None else eng.pool.accounting()

        PROFILER.register_pool(f"engine-{id(self):x}", _pool_accounting)

        rate_state = {"t": None, "tokens": 0}

        def _slo_stats():
            eng = ref()
            if eng is None:
                return None
            s = eng.stats()
            now = eng._clock()
            t0, tok0 = rate_state["t"], rate_state["tokens"]
            tokens = s.get("tokens_out", 0)
            rate_state["t"], rate_state["tokens"] = now, tokens
            if t0 is not None and now > t0:
                s["tokens_per_s"] = (tokens - tok0) / (now - t0)
            return s

        from paddle_tpu.obs.slo import WATCHDOG
        WATCHDOG.add_source(f"engine-{id(self):x}", _slo_stats)

    # ------------------------------------------------------------ admission
    def _pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    def _retry_hint(self) -> float:
        lats = list(self._lat)
        per_tok = (sum(lats) / len(lats)) if lats else 0.005
        return max(per_tok * self.page_size, 0.01)

    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None,
               trace_id: Optional[str] = None) -> GenRequest:
        """Admit one generation request. Raises the serving-typed
        errors at admission (``Rejected`` reasons: ``kv_capacity`` for
        a request the pool could NEVER hold, ``queue_full`` for a
        saturated wait queue); the request itself settles with tokens
        or a typed error. ``trace_id`` correlates the request through
        admission → slot → every decode step → settle (minted here
        when the front passed none)."""
        now = self._clock()
        trace_id = trace_id or obs_context.current().trace_id \
            or obs_context.new_trace_id()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt or int(max_new_tokens) < 1:
            raise ValueError("need a non-empty prompt and "
                             "max_new_tokens >= 1")
        total = len(prompt) + int(max_new_tokens)
        abs_deadline = (time.monotonic() + deadline) \
            if deadline is not None else None
        with self._cv:
            if not self._accepting:
                raise ServerClosed("decode engine is draining or "
                                   "stopped")
            if total > self.max_seq_len or \
                    self._pages_for(total) > self.pool.usable:
                self._counters["rejected_capacity"] += 1
                FLIGHT.record("mark", "engine/reject",
                              trace_id=trace_id, reason="kv_capacity")
                raise Rejected(
                    f"request needs {total} positions "
                    f"({self._pages_for(total)} KV pages) but the "
                    f"engine serves at most {self.max_seq_len} "
                    f"positions / {self.pool.usable} pages — it can "
                    "never be scheduled; shorten it",
                    retry_after=0.0, reason="kv_capacity")
            if len(self._waiting) >= self.max_waiting:
                self._counters["rejected_queue"] += 1
                retry = self._retry_hint()
                FLIGHT.record("mark", "engine/reject",
                              trace_id=trace_id, reason="queue_full")
                raise Rejected(
                    f"generation queue full ({self.max_waiting}); "
                    f"retry in {retry:.2f}s", retry_after=retry,
                    reason="queue_full")
            req = GenRequest(prompt, max_new_tokens, eos_id,
                             abs_deadline, now, trace_id=trace_id)
            self._counters["submitted"] += 1
            self._waiting.append(req)
            self._cv.notify_all()
        FLIGHT.record("mark", "engine/submit", trace_id=trace_id,
                      prompt_len=len(prompt),
                      max_new=int(max_new_tokens))
        return req

    # ------------------------------------------------------------ scheduling
    def _settle(self, req: GenRequest, state: str,
                error: Optional[ServingError] = None) -> None:
        req.state = state
        req.error = error
        req.finished_at = self._clock()
        req.done.set()

    def _index_slot_pages(self, slot: _Slot) -> None:
        """Leave the slot's COMPLETE teacher-forced pages behind in the
        prefix index (finish AND evict paths). Only rows the slot has
        actually FED are covered — ``seq[:pos]`` excludes rejected
        speculation rows and the not-yet-fed pending token."""
        if self.prefix is None or not slot.pages:
            return
        seq = slot.req.prompt + slot.req.tokens
        self.prefix.insert(seq[:slot.pos], slot.pages)

    def _finish(self, s: int, state: str,
                error: Optional[ServingError] = None) -> None:
        """Release slot ``s``: pages to the prefix index then back to
        the pool FIRST (the no-leak invariant), then settle the
        request."""
        slot = self.slots[s]
        if state in ("done", "cancelled"):
            # failed/closed slots may hold garbage KV (step failure) —
            # never index those pages
            self._index_slot_pages(slot)
        self.pool.free(slot.pages)
        slot.pages = []
        self._tables[s, :] = 0
        self._active[s, :] = False
        self._tokens[s, :] = 0
        self._positions[s, :] = 0
        self.slots[s] = None
        counter = {"done": "finished", "cancelled": "cancelled",
                   "failed": "failed", "closed": "closed"}.get(state)
        if state == "done":
            self._counters["finished"] += 1
        elif state == "cancelled":
            self._counters["cancelled"] += 1
        elif isinstance(error, Expired):
            self._counters["expired"] += 1
        elif isinstance(error, ServerClosed):
            self._counters["closed"] += 1
        if counter:
            global_counters.bump(f"serving/decode_{counter}")
        FLIGHT.record("mark", "engine/settle",
                      trace_id=slot.req.trace_id, state=state,
                      slot=s, generated=slot.req.num_generated,
                      error=repr(error)[:200] if error else None)
        self._settle(slot.req, state, error)
        with self._cv:
            self._cv.notify_all()

    def _evict(self, s: int) -> None:
        """Preempt slot ``s``: complete pages into the prefix index
        (re-admission walks them right back — preemption cost shrinks
        to the incomplete tail), refs to the pool, request back to the
        FRONT of the wait queue (it keeps its generated tokens and
        replays them on re-admission — greedy output is unchanged)."""
        slot = self.slots[s]
        self._index_slot_pages(slot)
        self.pool.free(slot.pages)
        slot.pages = []
        self._tables[s, :] = 0
        self._active[s, :] = False
        self.slots[s] = None
        req = slot.req
        req.state = "waiting"
        req.evictions += 1
        self._counters["preemptions"] += 1
        global_counters.bump("serving/decode_preemptions")
        journal_emit("engine", "preemption",
                     generated=req.num_generated,
                     evictions=req.evictions,
                     free_pages=self.pool.free_pages,
                     trace_id=req.trace_id)
        with self._cv:
            self._waiting.appendleft(req)

    def _reap(self, now: float) -> None:
        """Settle cancellations and deadline expiries — running slots
        and waiting requests both."""
        for s in range(self.num_slots):
            slot = self.slots[s]
            if slot is None:
                continue
            if slot.req._cancelled:
                self._finish(s, "cancelled")
            elif slot.req.deadline is not None and \
                    now > slot.req.deadline:
                self._finish(s, "failed", Expired(
                    f"deadline passed after {slot.req.num_generated} "
                    "generated tokens"))
        with self._cv:
            keep = deque()
            for req in self._waiting:
                if req._cancelled:
                    self._counters["cancelled"] += 1
                    FLIGHT.record("mark", "engine/settle",
                                  trace_id=req.trace_id,
                                  state="cancelled", where="waiting")
                    self._settle(req, "cancelled")
                elif req.deadline is not None and now > req.deadline:
                    self._counters["expired"] += 1
                    FLIGHT.record("mark", "engine/settle",
                                  trace_id=req.trace_id,
                                  state="expired", where="waiting")
                    self._settle(req, "failed", Expired(
                        "deadline passed while queued for a slot"))
                else:
                    keep.append(req)
            self._waiting = keep

    def _alloc_page(self) -> Optional[int]:
        """One page from the pool, reclaiming cold prefix-cache leaves
        (LRU, trie-only refcount) when the free list is dry — the trie
        gives pages back BEFORE any running request is preempted. With
        a spill store attached, cold pages route device->host
        (:meth:`_spill_cold_pages`) instead of being destroyed; the
        lossy ``evict_lru`` path remains the fallback when spilling
        can't free anything (no candidates, or a failed device read)."""
        page = self.pool.alloc()
        while page is None and self.prefix is not None:
            if self.spill is not None and self._spill_cold_pages(1):
                page = self.pool.alloc()
                continue
            freed = self.prefix.evict_lru(1)
            if not freed:
                return None
            self._counters["prefix_evicted_pages"] += len(freed)
            journal_emit("engine", "prefix_evict", pages=freed,
                         free_pages=self.pool.free_pages,
                         engine_step=self._steps)
            page = self.pool.alloc()
        return page

    # ------------------------------------------------------------- spill
    @staticmethod
    def _flatten_page(tag: str, tree, out: dict) -> None:
        """Pool-page pytree -> named host arrays (fp pools are bare
        arrays; int8 pools are {"q", "s"} dicts)."""
        if isinstance(tree, dict):
            for kk in sorted(tree):
                out[f"{tag}.{kk}"] = np.asarray(tree[kk])
        else:
            out[tag] = np.asarray(tree)

    @staticmethod
    def _unflatten_page(tag: str, like, payload: dict):
        import jax.numpy as jnp
        if isinstance(like, dict):
            return {kk: jnp.asarray(payload[f"{tag}.{kk}"])
                    for kk in like}
        return jnp.asarray(payload[tag])

    def _spill_cold_pages(self, n: int, avoid=None) -> int:
        """Spill up to ``n`` cold trie-only pages to the host store.
        The crash-safety ordering (serving/spill.py module doc): read
        + checksum first (no state changed), THEN evict the node and
        free the device page, THEN commit the entry — a crash at any
        point leaves the accounting balanced and can never leave a
        page both device-owned and host-stored.

        ``avoid`` (a token tuple) skips candidates on that path — the
        restore path passes the replay it is extending so making room
        can never spill the very match it is restoring into."""
        freed = 0
        cands = self.prefix.spill_candidates(
            n if avoid is None else n + 8)
        for path, page in cands:
            if freed >= n:
                break
            if avoid is not None and avoid[:len(path)] == path:
                continue
            hook = self._spill_interceptor
            if hook is not None:
                hook("read", path, page)
            try:
                payload: dict = {}
                k_page, v_page = self.paged.read_page(
                    self.k_pool, self.v_pool, page)
                self._flatten_page("k", k_page, payload)
                self._flatten_page("v", v_page, payload)
                entry = SpillEntry(payload)
            # ptlint: disable=R7(a failed device read falls back to the lossy evict path — the serving loop must not die for a cache optimization)
            except Exception as e:
                self._counters["kv_spill_integrity_drops"] += 1
                _SPILL_INTEGRITY.inc()
                journal_emit("engine", "spill_integrity",
                             reason="read_failed",
                             error=repr(e)[:200], page=page,
                             engine_step=self._steps)
                return freed
            if self.prefix.evict_exact(path) is None:
                continue               # node changed under us — skip
            if hook is not None:
                hook("commit", path, page)
            self.spill.put(path, entry)
            freed += 1
            self._counters["kv_pages_spilled"] += 1
            _SPILL_PAGES.inc()
            journal_emit("engine", "page_spill", page=page,
                         key_pages=len(path) // self.page_size,
                         spilled_now=len(self.spill),
                         free_pages=self.pool.free_pages,
                         engine_step=self._steps)
        return freed

    def _restore_spilled(self, replay) -> int:
        """Walk ``replay``'s token path past the trie match and restore
        consecutive spilled pages host->device BEFORE admission charges
        prefill — the spill-hit path of the two-tier cache. Each
        restore allocates a device page (which may cascade-spill colder
        pages), verifies the entry's checksum, uploads, and re-inserts
        the trie node; a torn entry is dropped and journaled
        (``engine/spill_integrity``) so the lookup degrades to a
        prefix miss."""
        ps = self.page_size
        limit = len(replay) - 1
        restored = 0
        avoid = tuple(int(t) for t in replay)
        while True:
            match = self.prefix.match(replay)
            nxt = match.matched + ps
            if nxt > limit:
                break
            key = avoid[:nxt]
            if not self.spill.has(key):
                break
            # make room by spilling colder OTHER branches only — never
            # the lossy evict path (destroying cache to restore cache)
            # and never this replay's own match (the ``avoid`` guard)
            page = self.pool.alloc()
            while page is None:
                if not self._spill_cold_pages(1, avoid=avoid):
                    break
                page = self.pool.alloc()
            if page is None:
                break                  # pool truly full — stay spilled
            entry = self.spill.pop(key)
            if entry is None:
                self.pool.free([page])
                break
            if not entry.verify():
                self.pool.free([page])
                self.spill.dropped_integrity += 1
                self._counters["kv_spill_integrity_drops"] += 1
                _SPILL_INTEGRITY.inc()
                journal_emit("engine", "spill_integrity",
                             reason="crc_mismatch",
                             key_pages=nxt // ps,
                             engine_step=self._steps)
                break
            try:
                k_page = self._unflatten_page("k", self.k_pool,
                                              entry.payload)
                v_page = self._unflatten_page("v", self.v_pool,
                                              entry.payload)
                self.k_pool, self.v_pool = self.paged.write_page(
                    self.k_pool, self.v_pool, k_page, v_page, page)
            # ptlint: disable=R7(a failed upload degrades to a prefix miss — never kills admission)
            except Exception as e:
                self.pool.free([page])
                self.spill.dropped_integrity += 1
                self._counters["kv_spill_integrity_drops"] += 1
                _SPILL_INTEGRITY.inc()
                journal_emit("engine", "spill_integrity",
                             reason="restore_write_failed",
                             error=repr(e)[:200], page=page,
                             engine_step=self._steps)
                break
            # trie takes the page over: insert refs it (2), dropping
            # our alloc ref leaves it trie-only (1) — exactly the
            # state it was spilled from
            self.prefix.insert(key, match.pages + [page])
            self.pool.free([page])
            self.spill.restored_count += 1
            restored += 1
            self._counters["kv_pages_restored"] += 1
            _SPILL_RESTORED.inc()
            journal_emit("engine", "page_restore", page=page,
                         key_pages=nxt // ps,
                         spilled_now=len(self.spill),
                         engine_step=self._steps)
        return restored

    def _attach_prefix(self, s: int, slot: _Slot, match) -> None:
        """Wire a PrefixMatch into slot ``s``: one slot ref per shared
        page, copy-on-write for an intra-page divergence, and the
        slot's feed position jumps past every matched token."""
        req = slot.req
        for p in match.pages:
            self.pool.ref(p)
            slot.pages.append(p)
        matched = match.matched
        if match.cow is not None:
            src, rows = match.cow
            # pin the source: the dst alloc below may reclaim trie
            # leaves, and the source IS a refcount-1 leaf right now
            self.pool.ref(src)
            dst = self._alloc_page()
            if dst is not None:
                try:
                    self.k_pool, self.v_pool = self.paged.copy_page(
                        self.k_pool, self.v_pool, src, dst)
                except Exception as e:  # pools rebuilt on next dispatch
                    self.pool.free([dst])
                    journal_emit("engine", "cow_copy_failure",
                                 error=repr(e)[:200],
                                 trace_id=req.trace_id)
                else:
                    slot.pages.append(dst)
                    matched += rows
                    self._counters["prefix_cow_copies"] += 1
                    _PREFIX_COW.inc()
                    if self.prefix is not None:
                        self.prefix.cow_hits += 1
            self.pool.free([src])       # unpin
        for j, p in enumerate(slot.pages):
            self._tables[s, j] = p
        slot.pos = matched
        hit = len(match.pages)
        miss = self._pages_for(len(slot.replay)) - hit
        self._counters["prefix_hit_pages"] += hit
        self._counters["prefix_miss_pages"] += max(miss, 0)
        if hit:
            _PREFIX_HIT.inc(hit)
        if miss > 0:
            _PREFIX_MISS.inc(miss)
        if self.prefix is not None:
            self.prefix.hit_pages += hit
            self.prefix.miss_pages += max(miss, 0)
        req.prefix_hit_pages = hit
        if matched:
            FLIGHT.record("mark", "engine/prefix_attach",
                          trace_id=req.trace_id, slot=s,
                          shared_pages=hit, matched_tokens=matched,
                          cow=match.cow is not None)

    def _admit(self) -> None:
        """Waiting -> free slots, gated on FREE PAGES: the queue head
        takes a slot only when the pool can carry its NOVEL pages to
        its first new token — shared-prefix pages cost nothing, and
        reclaimable trie leaves count as free (minus the pages this
        very match would pin)."""
        with self._cv:
            for s in range(self.num_slots):
                if self.slots[s] is not None or not self._waiting:
                    continue
                req = self._waiting[0]
                replay = req.prompt + req.tokens
                if self.spill is not None and len(self.spill) and \
                        self.prefix is not None:
                    # spill-hit TTFT path: restored pages join the
                    # match below, so admission charges only what is
                    # NOVEL beyond both tiers
                    self._restore_spilled(replay)
                match = self.prefix.match(replay) \
                    if self.prefix is not None else None
                shared = len(match.pages) if match is not None else 0
                need_now = self._pages_for(len(replay) + 1) - shared
                avail = self.pool.free_pages
                if self.prefix is not None:
                    avail += max(
                        0, self.prefix.reclaimable_pages() - shared)
                if need_now > avail:
                    break              # page-aware: head waits for pages
                self._waiting.popleft()
                req.state = "running"
                self._arrival_seq += 1
                slot = _Slot(req, self._arrival_seq)
                self.slots[s] = slot
                if match is not None and \
                        (match.pages or match.cow is not None):
                    self._attach_prefix(s, slot, match)
                FLIGHT.record("mark", "engine/admit",
                              trace_id=req.trace_id, slot=s,
                              replay=len(replay),
                              prefix_tokens=slot.pos)

    def _ensure_pages(self, plan: Dict[int, List[int]]) -> None:
        """Allocate each planned slot's pages through the LAST position
        its window will write; on pool exhaustion reclaim trie leaves
        first, then preempt the YOUNGEST slot (LIFO — oldest requests
        keep their progress) until the allocation succeeds."""
        for s in sorted(
                (i for i in range(self.num_slots)
                 if self.slots[i] is not None and i in plan),
                key=lambda i: self.slots[i].arrival):
            slot = self.slots[s]
            if slot is None:           # evicted by an earlier iteration
                continue
            last = slot.pos + len(plan[s]) - 1
            while len(slot.pages) * self.page_size <= last:
                page = self._alloc_page()
                if page is None:
                    victims = sorted(
                        (i for i in range(self.num_slots)
                         if self.slots[i] is not None),
                        key=lambda i: -self.slots[i].arrival)
                    assert victims, "pool exhausted with no slot held"
                    self._evict(victims[0])
                    if self.slots[s] is None:
                        break          # evicted ourselves
                    continue
                slot.pages.append(page)
                self._tables[s, len(slot.pages) - 1] = page

    # ----------------------------------------------------------- speculation
    def _draft_propose(self, active_idx: List[int]) -> Dict[int, List[int]]:
        """Run the draft model for up to spec_k proposals per caught-up
        slot: bounded rounds of the draft's own [S, W] jitted step,
        each round teacher-forcing committed tokens the draft hasn't
        seen (up to W per round) or chaining one proposal. Slots still
        prefilling the TARGET are skipped — their draft lanes catch up
        across later steps at W tokens a round."""
        if self.draft is None:
            return {}
        S, W = self.num_slots, self.window
        props: Dict[int, List[int]] = {}
        want: Dict[int, int] = {}
        for s in active_idx:
            slot = self.slots[s]
            if slot.pos < len(slot.replay) - 1:
                continue               # target still prefilling
            req = slot.req
            seq_len = len(req.prompt) + len(req.tokens)
            k_eff = min(self.spec_k,
                        req.max_new - req.num_generated - 1,
                        self.max_seq_len - 1 - slot.pos,
                        self.max_seq_len + 1 - seq_len)
            if k_eff > 0:
                props[s] = []
                want[s] = k_eff
        if not props:
            return {}
        toks = np.zeros((S, W), np.int32)
        poss = np.zeros((S, W), np.int32)
        act = np.zeros((S, W), np.bool_)
        for _ in range(self.spec_k + 2):
            toks[:, :] = 0
            poss[:, :] = 0
            act[:, :] = False
            fed: Dict[int, int] = {}   # slot -> tokens fed this round
            for s, got in props.items():
                slot = self.slots[s]
                if len(got) >= want[s]:
                    continue
                seq = slot.req.prompt + slot.req.tokens
                dp = slot.draft_pos
                if dp < len(seq):      # catch-up: feed committed chunk
                    c = min(W, len(seq) - dp)
                    toks[s, :c] = seq[dp:dp + c]
                else:                  # chain: feed the last proposal
                    c = 1
                    toks[s, 0] = got[-1]
                poss[s, :c] = np.arange(dp, dp + c)
                act[s, :c] = True
                fed[s] = c
            if not fed:
                break
            try:
                out, self._draft_kc, self._draft_vc = self.draft.step(
                    self._draft_kc, self._draft_vc, toks, poss, act)
                out = np.asarray(out)
            # ptlint: disable=R7(draft failures must not kill the serving loop — the target path continues unassisted)
            except Exception as e:
                self._counters["draft_failures"] += 1
                journal_emit("engine", "draft_failure",
                             error=repr(e)[:400],
                             engine_step=self._steps)
                self._draft_kc, self._draft_vc = \
                    self.draft.init_caches()
                for s in props:
                    if self.slots[s] is not None:
                        self.slots[s].draft_pos = 0
                return {}
            for s, c in fed.items():
                slot = self.slots[s]
                seq_len = len(slot.req.prompt) + len(slot.req.tokens)
                slot.draft_pos += c
                if slot.draft_pos >= seq_len:
                    # the last fed row predicts the next token: the
                    # first/next proposal in the chain
                    props[s].append(int(out[s, c - 1]))
        return {s: p for s, p in props.items() if p}

    # ------------------------------------------------------------- the loop
    def step(self) -> bool:
        """One engine iteration: reap, admit, draft-propose, window-
        plan, page-ensure, ONE jitted target dispatch, bookkeep.
        Returns True iff a device step ran. Single-threaded by
        contract: the engine thread in serving mode, the caller in
        sync mode."""
        interceptor = self._step_interceptor
        if interceptor is not None:
            interceptor(self._steps)
        now = self._clock()
        self._reap(now)
        self._admit()
        active_idx = [s for s in range(self.num_slots)
                      if self.slots[s] is not None]
        if not active_idx:
            return False
        props = self._draft_propose(active_idx)
        # window plan: a replay chunk (multi-token prefill) or the
        # pending token + the draft's proposals (speculative verify)
        W = self.window
        plan: Dict[int, List[int]] = {}
        for s in active_idx:
            slot = self.slots[s]
            if slot.pos < len(slot.replay) - 1:
                wlen = min(W, len(slot.replay) - slot.pos)
                plan[s] = slot.replay[slot.pos:slot.pos + wlen]
            else:
                p_s = props.get(s, [])[:W - 1]
                room = self.max_seq_len - 1 - slot.pos
                plan[s] = [slot.next_input()] + p_s[:max(room, 0)]
        self._ensure_pages(plan)
        live = [s for s in active_idx
                if self.slots[s] is not None and s in plan]
        if not live:
            return False
        self._active[:, :] = False
        self._tokens[:, :] = 0
        self._positions[:, :] = 0
        for s in live:
            slot = self.slots[s]
            w = len(plan[s])
            self._tokens[s, :w] = plan[s]
            self._positions[s, :w] = np.arange(slot.pos, slot.pos + w)
            self._active[s, :w] = True
        key = self._key0
        if self.temperature is not None:
            import jax
            key = jax.random.fold_in(self._key0, self._steps)
        try:
            with stat_timer("serving/decode_step"):
                nxt, self.k_pool, self.v_pool = self.paged.step(
                    self.k_pool, self.v_pool, self._tokens,
                    self._positions, self._tables, self._active, key)
                nxt = np.asarray(nxt)  # the ONE host sync per step
        # ptlint: disable=R7(serving boundary — in-flight requests settle typed and the pools rebuild; the engine thread must never die)
        except Exception as e:
            self._recover_from_step_failure(e)
            return False
        t_after = self._clock()
        with self._cv:
            self._steps += 1
            self._active_steps_sum += len(live)
        if PROFILER.enabled:
            PROFILER.on_step("decode")
        for s in live:
            slot = self.slots[s]
            toks = plan[s]
            w = len(toks)
            fed = slot.pos
            req = slot.req
            # one compact flight record per slot-step: the "each decode
            # step" link of the request's trace chain — a postmortem
            # bundle reconstructs the request's whole schedule from
            # these by trace_id (tests/test_flight.py acceptance)
            FLIGHT.record("mark", "engine/slot_step",
                          trace_id=req.trace_id,
                          engine_step=self._steps, slot=s, pos=fed,
                          width=w)
            with self._cv:
                self._cache_tokens_read += sum(
                    fed + j + 1 for j in range(w))
            if fed < len(slot.replay) - 1:
                # replay chunk: all rows teacher-forced; the last row
                # commits one token iff it reached the replay tail
                commits = []
                n_prefill = min(w, len(slot.replay) - 1 - fed)
                with self._cv:
                    self._counters["prefill_tokens"] += n_prefill
                slot.pos = fed + w
                if fed + w == len(slot.replay):
                    commits = [int(nxt[s, w - 1])]
            else:
                # speculative verify: outs[j] is the target's choice
                # after feeding tokens 0..j. Proposal j (toks[j+1]) is
                # accepted iff it IS that choice; the first rejection
                # ends the run and its row becomes dead weight the
                # kv_len mask never reads.
                m = w - 1
                outs = [int(nxt[s, j]) for j in range(w)]
                commits = [outs[0]]
                a = 0
                while a < m and toks[a + 1] == commits[-1]:
                    commits.append(outs[a + 1])
                    a += 1
                if m:
                    with self._cv:
                        self._counters["spec_proposed_tokens"] += m
                        self._counters["spec_accepted_tokens"] += a
                    _SPEC_PROPOSED.inc(m)
                    if a:
                        _SPEC_ACCEPTED.inc(a)
                    req.accepted_tokens += a
                seq_before = len(req.prompt) + len(req.tokens)
                slot.draft_pos = min(slot.draft_pos, seq_before + a)
            if not commits:
                continue
            done = False
            n_commit = 0
            with self._cv:
                if req.first_token_at is None:
                    req.first_token_at = t_after
                    self._ttft.append(t_after - req.submitted_at)
                dt = (t_after - slot.last_token_t) \
                    if slot.last_token_t is not None else None
                slot.last_token_t = t_after
                for tok in commits:
                    req.tokens.append(tok)
                    slot.last_tok = tok
                    n_commit += 1
                    self._counters["tokens_out"] += 1
                    if (req.eos_id is not None and tok == req.eos_id) \
                            or req.num_generated >= req.max_new:
                        done = True
                        break
                if dt is not None:
                    for _ in range(n_commit):
                        self._lat.append(dt / n_commit)
            global_counters.bump("serving/decode_tokens", n_commit)
            if fed >= len(slot.replay) - 1:
                # keep only the fed rows that match the committed
                # sequence: pending token + (n_commit - 1) accepted
                slot.pos = fed + n_commit
                slot.draft_pos = min(slot.draft_pos, fed + n_commit)
            if done:
                self._finish(s, "done")
        return True

    def _recover_from_step_failure(self, exc: Exception) -> None:
        """A failed dispatch may have consumed the (donated) pools:
        settle everything in flight with a typed error, then rebuild
        pools + free-list + prefix index + draft caches so fresh
        traffic can still be served."""
        in_flight = [self.slots[s].req.trace_id
                     for s in range(self.num_slots)
                     if self.slots[s] is not None]
        with self._cv:
            self._counters["step_failures"] += 1
            waiting_ids = [r.trace_id for r in self._waiting]
        err = ServingError(f"decode step failed: {exc}")
        for s in range(self.num_slots):
            if self.slots[s] is not None:
                self._finish(s, "failed", err)
        with self._cv:
            while self._waiting:
                req = self._waiting.popleft()
                FLIGHT.record("mark", "engine/settle",
                              trace_id=req.trace_id, state="failed",
                              where="waiting")
                self._settle(req, "failed", err)
        self.k_pool, self.v_pool = self.paged.init_pools()
        self.pool = PagePool(self.pool.num_pages)
        if self.prefix is not None:
            # the trie indexed pages of the DEAD pool: forget them all
            # and repoint at the rebuilt allocator
            self.prefix.reset()
            self.prefix.pool = self.pool
        if self.spill is not None:
            # host entries were carved from the dead trie — NEVER
            # restore across a rebuild (torn-state resurrection)
            self._counters["kv_spill_cleared"] += self.spill.clear()
        if self.draft is not None:
            self._draft_kc, self._draft_vc = self.draft.init_caches()
        self._tables[:, :] = 0
        self._active[:, :] = False
        # journaled AFTER the typed settles so the auto-dumped bundle
        # (obs/flight.py trigger) contains each victim's COMPLETE chain
        # — submit → admit → every slot_step → settle(failed) — plus
        # this record naming the in-flight trace ids at fault time
        journal_emit("engine", "step_failure", error=repr(exc)[:400],
                     trace_ids=in_flight, waiting_trace_ids=waiting_ids,
                     engine_step=self._steps)

    def _has_work(self) -> bool:
        return any(s is not None for s in self.slots) or \
            bool(self._waiting)

    def run(self, timeout: float = 120.0) -> None:
        """Synchronous drive: step until every submitted request has
        settled (the deterministic test/bench mode)."""
        deadline = time.monotonic() + timeout
        while self._has_work():
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"engine did not drain within {timeout}s "
                    f"({self.stats()})")

    # ------------------------------------------------------------ lifecycle
    def warmup(self) -> dict:
        """Resolve the decode executables NOW, before any request is
        admitted — the warm-start plane's engine hook (docs/
        robustness.md "Warm start & artifact integrity").

        Dispatches one all-inactive step through the target (and
        draft, when speculating): inactive slots write only the
        reserved null page / null row, so pools are semantically
        untouched, and the dispatch shapes are exactly the serving
        shapes — the executable resolved here IS the one every later
        step reuses. With a warm artifact store the whole call is
        zero-compile (deserialized executables trace nothing); cold,
        it pays the compile up front and backfills the store, so
        first-token latency never pays it. Returns resolver stats."""
        from paddle_tpu.artifacts import EXECUTABLES
        S, W = self.num_slots, self.window
        z = np.zeros((S, W), np.int32)
        inactive = np.zeros((S, W), np.bool_)
        _, self.k_pool, self.v_pool = self.paged.step(
            self.k_pool, self.v_pool, z, z, self._tables, inactive)
        if self.draft is not None:
            _, self._draft_kc, self._draft_vc = self.draft.step(
                self._draft_kc, self._draft_vc, z, z, inactive)
        return dict(EXECUTABLES.stats(), warm_start=self.warm_start)

    def start(self) -> "DecodeEngine":
        with self._cv:
            if self._thread is not None:
                return self
            self._stopping = False
            self._accepting = True
            t = threading.Thread(target=self._loop,
                                 name="pt-serve-decode", daemon=True)
            self._thread = t
            t.start()
        return self

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._close_now:
                    break
                if not self._has_work():
                    if self._stopping:
                        return
                    self._cv.wait(0.05)
                    continue
            self.step()
        self._close_all()

    def _close_all(self) -> None:
        """Settle everything in flight with ServerClosed and return
        every page — runs on the STEPPING thread, so it never races a
        dispatch."""
        for s in range(self.num_slots):
            if self.slots[s] is not None:
                self._finish(s, "failed", ServerClosed(
                    "engine shut down mid-generation"))
        with self._cv:
            while self._waiting:
                req = self._waiting.popleft()
                self._counters["closed"] += 1
                self._settle(req, "failed", ServerClosed(
                    "engine shut down before this request ran"))

    def drain_admission(self) -> None:
        """Deploy-drain: stop ADMITTING (submit raises ServerClosed)
        while the loop keeps stepping everything already in flight —
        the fleet router's POST /admin/drain leg
        (docs/robustness.md "Serving fleet"). Reversible via
        :meth:`resume_admission`; full stop stays :meth:`shutdown`."""
        with self._cv:
            self._accepting = False

    def resume_admission(self) -> None:
        """Re-open admission after :meth:`drain_admission` (no-op on a
        stopping engine)."""
        with self._cv:
            if not self._stopping:
                self._accepting = True
                self._cv.notify_all()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 30.0) -> None:
        """Stop accepting. With ``drain`` in-flight generation
        completes; without it everything settles ServerClosed and the
        pages return to the pool immediately."""
        with self._cv:
            self._accepting = False
            self._close_now = self._close_now or not drain
            self._stopping = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout)
            with self._cv:
                self._thread = None
        elif drain:
            self.run(timeout=timeout if timeout is not None else 120.0)
        else:
            self._close_all()

    # ------------------------------------------------------------ snapshots
    @staticmethod
    def _percentile(vals: List[float], q: float) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def page_accounting(self) -> dict:
        """Pool truth vs slot + trie holdings — the chaos suite's
        no-leak assertion reads ``leaked`` (== 0 always) and
        cross-checks ``refs_total`` == ``held_by_slots`` +
        ``held_by_trie`` (zero refcount underflows). With a spill
        store the dict grows the SECOND tier (``spilled``,
        ``spill_capacity``, ...): the extended invariant
        (tests/test_serving_faults.py ``assert_pool_balanced``) also
        proves host-tier conservation — spills in == restores +
        integrity drops + LRU drops + recovery clears + still-resident
        entries."""
        acc = self.pool.accounting()
        acc["held_by_slots"] = sum(
            len(s.pages) for s in self.slots if s is not None)
        acc["held_by_trie"] = self.prefix.page_count() \
            if self.prefix is not None else 0
        if self.spill is not None:
            acc.update(self.spill.accounting())
            acc["spill_cleared"] = self._counters["kv_spill_cleared"]
        else:
            acc["spilled"] = 0
            acc["spill_capacity"] = 0
        return acc

    def stats(self) -> dict:
        with self._cv:
            counters = dict(self._counters)
            lat = list(self._lat)
            ttft = list(self._ttft)
            waiting = len(self._waiting)
            steps = self._steps
            active_sum = self._active_steps_sum
            cache_read = self._cache_tokens_read
        active = sum(1 for s in self.slots if s is not None)
        util = (active_sum / (steps * self.num_slots)) if steps else 0.0
        shared = self.pool.shared_pages
        leaked = self.pool.accounting()["leaked"]
        _PREFIX_SHARED.set(shared)
        spilled_now = len(self.spill) if self.spill is not None else 0
        spill_cap = self.spill.capacity if self.spill is not None else 0
        _SPILLED_NOW.set(spilled_now)
        out = dict(counters)
        out.update({
            "slots": self.num_slots,
            "active_slots": active,
            "waiting": waiting,
            "slot_utilization": round(util, 4),
            "kv_pages_total": self.pool.usable,
            "kv_pages_free": self.pool.free_pages,
            "kv_pages_used": self.pool.used_pages,
            "kv_pages_shared": shared,
            # the no-leak invariant, scrapeable: survivors of a chaos
            # storm must show 0 here (tests/test_fleet_faults.py reads
            # it over GET /stats)
            "kv_pages_leaked": leaked,
            # trie-held pages _admit would evict on demand: a router
            # judging this replica's headroom off the free list alone
            # would livelock after a prefix-heavy burst (the trie only
            # yields pages under admission pressure, which a gated
            # router never applies)
            "kv_pages_reclaimable": self.prefix.reclaimable_pages()
            if self.prefix is not None else 0,
            "kv_page_high_water": self.pool.high_water,
            # the second tier: current host-resident pages, capacity,
            # and the lossless headroom the router counts toward this
            # replica's admission (fleet/balance.py)
            "kv_pages_spilled_now": spilled_now,
            "kv_spill_capacity": spill_cap,
            "kv_spill_headroom": max(0, spill_cap - spilled_now),
            "kv_quant": self.kv_quant or "none",
            "kv_quant_bits": 8 if self.kv_quant == "int8" else
            int(np.dtype(getattr(self.paged, "dtype", "float32"))
                .itemsize) * 8,
            "page_size": self.page_size,
            "window": self.window,
            "spec_k": self.spec_k,
            "prefix_nodes": self.prefix.page_count()
            if self.prefix is not None else 0,
            "steps": steps,
            "active_slot_steps": active_sum,
            "cache_tokens_read": cache_read,
            "token_latency_p50_ms":
                round(self._percentile(lat, 0.50) * 1e3, 3),
            "token_latency_p99_ms":
                round(self._percentile(lat, 0.99) * 1e3, 3),
            "ttft_p50_ms": round(self._percentile(ttft, 0.50) * 1e3, 3),
        })
        return out
