"""Hardened inference serving (docs/robustness.md "Serving").

Admission-controlled serving over merged inference artifacts: bounded
queue with backpressure, per-request deadlines, a sliding-window
circuit breaker, graceful drain, and health/stats snapshots. The C-ABI
twin of this discipline lives in paddle_tpu/capi_host.py (typed error
codes, no exception crosses into C).

Generation rides the continuous-batching decode engine
(serving/engine.py): a fixed-shape jitted decode step over a paged KV
cache, requests joining/leaving mid-flight, admission scheduled by free
KV pages — docs/perf.md "Continuous batching"."""

from paddle_tpu.serving.breaker import CircuitBreaker
from paddle_tpu.serving.engine import DecodeEngine, GenRequest, PagePool
from paddle_tpu.serving.http import build_http_server, prometheus_text
from paddle_tpu.serving.prefix import PrefixIndex, PrefixMatch
from paddle_tpu.serving.server import (Expired, InferenceServer, Rejected,
                                       ServerClosed, ServingError)

__all__ = ["CircuitBreaker", "InferenceServer", "ServingError",
           "Rejected", "Expired", "ServerClosed", "build_http_server",
           "prometheus_text", "DecodeEngine", "GenRequest", "PagePool",
           "PrefixIndex", "PrefixMatch"]
