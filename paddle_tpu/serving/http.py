"""Minimal JSON/HTTP front for InferenceServer (the `paddle_tpu serve`
CLI's transport; stdlib-only so the serving path adds no dependency).

Endpoints:
  GET  /health          -> InferenceServer.health()
  GET  /stats           -> InferenceServer.stats()
  POST /infer           -> body {"rows": [[f32...], ...],
                                 "deadline_ms": optional}
                           200 {"outputs": [[...], ...]}

Admission failures map onto transport status codes:
  429 + Retry-After     queue full (backpressure)
  503 + Retry-After     circuit breaker open (load shed) / draining
  504                   deadline expired
  400                   malformed payload
  500                   forward failed
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from paddle_tpu.serving.server import (Expired, InferenceServer, Rejected,
                                       ServerClosed, ServingError)


def build_http_server(server: InferenceServer, host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    """An HTTP server bound to (host, port) — port 0 picks a free one
    (see .server_address). Caller runs .serve_forever() (usually on a
    thread) and .shutdown()."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):     # quiet; stats() has it
            pass

        def _json(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._json(200, server.health())
            elif self.path == "/stats":
                self._json(200, server.stats())
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/infer":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                rows = req["rows"]
                if not isinstance(rows, list) or not rows:
                    raise ValueError("rows must be a non-empty list")
                deadline = req.get("deadline_ms")
                deadline = float(deadline) / 1e3 \
                    if deadline is not None else None
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            try:
                out = server.infer_rows(rows, deadline)
            except Rejected as e:
                code = 429 if e.reason == "queue_full" else 503
                self._json(code, {"error": str(e), "reason": e.reason,
                                  "retry_after": e.retry_after},
                           headers=[("Retry-After",
                                     f"{max(e.retry_after, 0.01):.3f}")])
                return
            except Expired as e:
                self._json(504, {"error": str(e)})
                return
            except ServerClosed as e:
                self._json(503, {"error": str(e), "reason": "draining"})
                return
            except ServingError as e:
                self._json(500, {"error": str(e)})
                return
            except ValueError as e:       # ragged / non-numeric rows
                self._json(400, {"error": f"bad request: {e}"})
                return
            self._json(200, {"outputs": np.asarray(out).tolist()})

    return ThreadingHTTPServer((host, port), Handler)
