"""Minimal JSON/HTTP front for InferenceServer (the `paddle_tpu serve`
CLI's transport; stdlib-only so the serving path adds no dependency).

Endpoints:
  GET  /health          -> InferenceServer.health()
  GET  /stats           -> InferenceServer.stats()
  GET  /metrics         -> Prometheus text exposition through the
                           unified registry (paddle_tpu/obs/metrics.py):
                           serving counters/latency gauges (and, with a
                           decode engine attached, the KV-page and
                           slot-utilization gauges a fleet scheduler
                           acts on) PLUS the global registry — trainer,
                           data-pipeline and fault domains — so one
                           scrape sees the whole process
  GET  /events          -> the structured event journal's in-memory
                           ring (paddle_tpu/obs/events.py;
                           ?n=100&domain=...&kind=... filters;
                           ?since_seq=N pages forward from a cursor —
                           the response's "last_seq" is the next one)
  GET  /flight          -> the flight recorder's postmortem bundle on
                           demand (paddle_tpu/obs/flight.py;
                           `paddle_tpu obs dump --url` fetches this)
  POST /infer           -> body {"rows": [[f32...], ...],
                                 "deadline_ms": optional}
                           200 {"outputs": [[...], ...]}
  POST /generate        -> body {"prompt": [int...],
                                 "max_new_tokens": int,
                                 "eos_id": optional,
                                 "deadline_ms": optional,
                                 "stream": optional bool}
                           200 {"tokens": [int...],
                                "prefix_hit_pages": int,
                                "accepted_tokens": int} — routed
                           through the continuous-batching decode
                           engine; the two extra fields report KV
                           pages reused from the shared-prefix cache
                           and draft tokens the target accepted
                           (501 when no engine is attached).
                           With "stream": true the 200 body is
                           close-delimited NDJSON — one
                           {"token": t} line per generated token as
                           it lands, then a terminal {"done": true,
                           "tokens": [...], ...} record (or an
                           {"error": ...} record when the request
                           settles with a typed error mid-stream).
                           The fleet router (paddle_tpu/fleet/)
                           consumes this mode; a torn stream (no
                           terminal record) is its failover trigger.
  POST /admin/drain     -> stop ADMITTING (503 reason "draining" on
                           new work) while in-flight requests settle
                           and the transport stays up — the router's
                           drain/deploy leg. POST /admin/resume
                           re-opens admission. Both return /health.
  POST /admin/quit      -> ask the daemon to exit cleanly (drain →
                           leave → close, same order as SIGTERM) —
                           the rolling deploy's restart primitive for
                           supervisor-managed replicas (the supervisor
                           respawns; fleet/autopilot.py drives it).
                           Answers 200 {"quitting": true} BEFORE the
                           teardown starts; 501 when the embedding
                           (CLI daemon) wired no quit hook.

Every /infer and /generate request gets ONE trace_id at this front —
taken from an ``X-Trace-Id`` header or body ``trace_id`` field when a
gateway propagates its own, minted fresh otherwise — which flows
through admission, queue wait, the engine slot, every decode step and
settle/shed (docs/observability.md "Trace context & postmortems"), and
is echoed back in the response body + ``X-Trace-Id`` header.

Admission failures map onto transport status codes:
  429 + Retry-After     queue full (backpressure)
  503 + Retry-After     circuit breaker open (load shed) / draining /
                        KV pool can never hold the request
  504                   deadline expired
  400                   malformed payload
  500                   forward failed
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.obs import context as obs_context
from paddle_tpu.obs.events import JOURNAL
from paddle_tpu.obs.events import emit as journal_emit
from paddle_tpu.obs.metrics import REGISTRY, SampleFamily, stats_families
from paddle_tpu.serving.server import (Expired, InferenceServer, Rejected,
                                       ServerClosed, ServingError)

#: stats() leaf keys with cumulative (counter) semantics; every other
#: numeric leaf is a gauge. The flattened names these produce
#: (paddle_tpu_serving_served, paddle_tpu_serving_engine_finished, the
#: KV-page/slot gauges...) are test-pinned — keep them stable.
_COUNTER_KEYS = {
    # InferenceServer counters
    "served", "rejected_full", "rejected_breaker", "rejected_oom",
    "oom_events", "expired", "failed", "closed",
    # DecodeEngine counters
    "submitted", "finished", "cancelled", "preemptions",
    "rejected_queue", "rejected_capacity", "step_failures",
    "tokens_out", "prefill_tokens", "steps", "cache_tokens_read",
    "trips",
    # round-9 prefix-cache / speculative-decoding counters
    "prefix_hit_pages", "prefix_miss_pages", "prefix_cow_copies",
    "prefix_evicted_pages", "spec_proposed_tokens",
    "spec_accepted_tokens", "draft_failures",
}


def replica_identity(endpoint: str = "") -> dict:
    """The labels that join this replica's series across scrapers and
    the fleet router without out-of-band config: the process's run_id
    (obs context), its host tag (PADDLE_TPU_HOST) and the HTTP
    endpoint it serves on."""
    return {"run_id": obs_context.ensure_run_id(),
            "host": obs_context.get_host(),
            "endpoint": endpoint or ""}


def prometheus_text(server: InferenceServer,
                    prefix: str = "paddle_tpu_serving",
                    endpoint: str = "") -> str:
    """Render ``server.stats()`` (engine sub-dict included) PLUS the
    global metrics registry as Prometheus text exposition 0.0.4 — the
    ONE exposition path (paddle_tpu/obs/metrics.py); the ad-hoc PR-6
    flattening lives on as obs.metrics.stats_families with the same
    backward-compatible names. The constant-1
    ``paddle_tpu_serving_replica_info`` gauge carries the replica's
    identity labels (run_id/host/endpoint) so Prometheus joins and
    the fleet router can identify per-replica series from the scrape
    alone."""
    info = SampleFamily(
        f"{prefix}_replica_info", "gauge",
        "replica identity (constant 1; labels are the payload)")
    info.add({k: str(v) for k, v in
              replica_identity(endpoint).items()}, 1.0)
    return REGISTRY.exposition(
        extra=stats_families(prefix, server.stats(), _COUNTER_KEYS)
        + [info])


def build_http_server(server: InferenceServer, host: str = "127.0.0.1",
                      port: int = 0,
                      on_quit=None) -> ThreadingHTTPServer:
    """An HTTP server bound to (host, port) — port 0 picks a free one
    (see .server_address). Caller runs .serve_forever() (usually on a
    thread) and .shutdown(). ``on_quit`` (no-arg callable) arms POST
    /admin/quit — the CLI daemon passes its orderly-exit trigger so a
    rolling deploy can restart replicas over HTTP."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):     # quiet; stats() has it
            pass

        def _endpoint(self) -> str:
            h, p = self.server.server_address[:2]
            return f"http://{h}:{p}"

        def _json(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _trace_id(self, req: dict) -> str:
            """The request's end-to-end correlation id, minted HERE at
            the front (docs/observability.md "Trace context"): an
            ``X-Trace-Id`` header or body ``trace_id`` field wins (a
            client/gateway propagating its own id), else a fresh one.
            Echoed back in every response so the client can quote it
            at the journal / flight recorder."""
            tid = self.headers.get("X-Trace-Id") or req.get("trace_id")
            return str(tid) if tid else obs_context.new_trace_id()

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/health":
                payload = server.health()
                payload["replica"] = replica_identity(self._endpoint())
                self._json(200, payload)
            elif url.path == "/stats":
                self._json(200, server.stats())
            elif url.path == "/metrics":
                body = prometheus_text(
                    server, endpoint=self._endpoint()).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/events":
                qs = parse_qs(url.query)
                try:
                    n = int(qs.get("n", ["100"])[0])
                    since = qs.get("since_seq", [None])[0]
                    since = int(since) if since is not None else None
                except ValueError:
                    self._json(400, {"error": "n/since_seq must be "
                                              "integers"})
                    return
                self._json(200, {"events": JOURNAL.tail(
                    n, domain=qs.get("domain", [None])[0],
                    kind=qs.get("kind", [None])[0], since_seq=since),
                    "last_seq": JOURNAL.last_seq})
            elif url.path == "/flight":
                from paddle_tpu.obs.flight import FLIGHT
                self._json(200, FLIGHT.bundle(reason="http"))
            elif url.path == "/profile":
                # live per-phase/MFU/memory snapshot + SLO state;
                # ?deep_steps=N arms a jax.profiler.trace window over
                # the next N decode steps (obs/profile.py)
                from paddle_tpu.obs.profile import PROFILER
                from paddle_tpu.obs.slo import WATCHDOG
                qs = parse_qs(url.query)
                payload = {}
                deep = qs.get("deep_steps", [None])[0]
                if deep is not None:
                    try:
                        payload["armed_trace_dir"] = \
                            PROFILER.arm_window(int(deep))
                    except ValueError:
                        self._json(400, {"error": "deep_steps must "
                                                  "be an integer"})
                        return
                payload["profile"] = PROFILER.snapshot()
                payload["slo"] = WATCHDOG.snapshot()
                self._json(200, payload)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def _do_generate(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                prompt = req["prompt"]
                if not isinstance(prompt, list) or not prompt:
                    raise ValueError("prompt must be a non-empty list "
                                     "of token ids")
                max_new = int(req["max_new_tokens"])
                if max_new < 1:
                    raise ValueError("max_new_tokens must be >= 1")
                eos_id = req.get("eos_id")
                eos_id = int(eos_id) if eos_id is not None else None
                deadline = req.get("deadline_ms")
                deadline = float(deadline) / 1e3 \
                    if deadline is not None else None
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            if server.engine is None:
                self._json(501, {"error": "no decode engine attached "
                                          "to this server"})
                return
            stream = bool(req.get("stream"))
            tid = self._trace_id(req)
            hdr = [("X-Trace-Id", tid)]
            try:
                with obs_context.bind(trace_id=tid):
                    gen = server.submit_generate(prompt, max_new,
                                                 eos_id=eos_id,
                                                 deadline=deadline,
                                                 trace_id=tid)
                    if stream:
                        self._stream_generate(gen, tid)
                        return
                    toks = gen.get()
            except Rejected as e:
                code = 429 if e.reason == "queue_full" else 503
                self._json(code, {"error": str(e), "reason": e.reason,
                                  "retry_after": e.retry_after,
                                  "trace_id": tid},
                           headers=hdr + [
                               ("Retry-After",
                                f"{max(e.retry_after, 0.01):.3f}")])
                return
            except Expired as e:
                self._json(504, {"error": str(e), "trace_id": tid},
                           headers=hdr)
                return
            except ServerClosed as e:
                self._json(503, {"error": str(e), "reason": "draining",
                                 "trace_id": tid}, headers=hdr)
                return
            except ServingError as e:
                self._json(500, {"error": str(e), "trace_id": tid},
                           headers=hdr)
                return
            self._json(200, {"tokens": [int(t) for t in toks],
                             "prefix_hit_pages": gen.prefix_hit_pages,
                             "accepted_tokens": gen.accepted_tokens,
                             "trace_id": tid}, headers=hdr)

        def _stream_generate(self, gen, tid: str) -> None:
            """Relay tokens as the engine produces them: one NDJSON
            line per token, then the terminal done/error record. The
            response is close-delimited (HTTP/1.0, no Content-Length)
            — a TEAR before the terminal record is how a fleet router
            distinguishes a dead replica from a settled request. A
            client disconnect cancels the generation (stream
            semantics: the engine returns the pages and settles with
            the tokens so far)."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("X-Trace-Id", tid)
            self.end_headers()
            # the replica's side of the fleet trace: a hop that starts
            # here and never journals a settle is one the process lost
            # mid-stream (SIGKILL) — `paddle_tpu trace merge` over the
            # router's + replicas' journals shows exactly that shape
            journal_emit("serving", "hop", trace_id=tid, phase="start")

            def _line(payload: dict) -> None:
                self.wfile.write(json.dumps(payload).encode() + b"\n")
                self.wfile.flush()

            sent = 0
            settled = False
            try:
                while True:
                    finished = gen.done.wait(0.005)
                    toks = list(gen.tokens)
                    while sent < len(toks):
                        _line({"token": int(toks[sent])})
                        sent += 1
                    if finished:
                        break
                try:
                    final = gen.get(timeout=1.0)
                except Rejected as e:
                    _line({"error": str(e), "reason": e.reason,
                           "retry_after": e.retry_after,
                           "trace_id": tid})
                    journal_emit("serving", "hop", trace_id=tid,
                                 phase="error", reason="rejected")
                    settled = True
                    return
                except Expired as e:
                    _line({"error": str(e), "expired": True,
                           "trace_id": tid})
                    journal_emit("serving", "hop", trace_id=tid,
                                 phase="error", reason="expired")
                    settled = True
                    return
                except ServerClosed as e:
                    _line({"error": str(e), "reason": "draining",
                           "trace_id": tid})
                    journal_emit("serving", "hop", trace_id=tid,
                                 phase="error", reason="draining")
                    settled = True
                    return
                except ServingError as e:
                    _line({"error": str(e), "trace_id": tid})
                    journal_emit("serving", "hop", trace_id=tid,
                                 phase="error", reason="serving_error")
                    settled = True
                    return
                _line({"done": True,
                       "tokens": [int(t) for t in final],
                       "prefix_hit_pages": gen.prefix_hit_pages,
                       "accepted_tokens": gen.accepted_tokens,
                       "trace_id": tid})
                journal_emit("serving", "hop", trace_id=tid,
                             phase="settle", tokens=len(final))
                settled = True
            except (BrokenPipeError, ConnectionError, OSError):
                gen.cancel()          # client went away mid-stream
                journal_emit("serving", "hop", trace_id=tid,
                             phase="torn", streamed=sent)
                settled = True
            finally:
                if not settled:
                    # an unexpected exception is unwinding through the
                    # handler: terminate the hop machine (ptproto
                    # serving_hop) so only a process LOSS can leave a
                    # start with no terminal in the journal
                    try:
                        gen.cancel()
                    except Exception:  # noqa: BLE001
                        pass
                    journal_emit("serving", "hop", trace_id=tid,
                                 phase="torn", streamed=sent,
                                 reason="exception")

        def do_POST(self):
            if self.path == "/generate":
                self._do_generate()
                return
            if self.path == "/admin/drain":
                payload = server.drain()
                payload["replica"] = replica_identity(self._endpoint())
                self._json(200, payload)
                return
            if self.path == "/admin/resume":
                payload = server.resume()
                payload["replica"] = replica_identity(self._endpoint())
                self._json(200, payload)
                return
            if self.path == "/admin/quit":
                if on_quit is None:
                    self._json(501, {"error": "no quit hook wired "
                                              "(in-process server?)"})
                    return
                # answer FIRST — the teardown closes this transport
                self._json(200, {
                    "quitting": True,
                    "replica": replica_identity(self._endpoint())})
                threading.Thread(target=on_quit, daemon=True,
                                 name="pt-serving-quit").start()
                return
            if self.path != "/infer":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                rows = req["rows"]
                if not isinstance(rows, list) or not rows:
                    raise ValueError("rows must be a non-empty list")
                deadline = req.get("deadline_ms")
                deadline = float(deadline) / 1e3 \
                    if deadline is not None else None
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            tid = self._trace_id(req)
            hdr = [("X-Trace-Id", tid)]
            try:
                with obs_context.bind(trace_id=tid):
                    out = server.infer_rows(rows, deadline,
                                            trace_id=tid)
            except Rejected as e:
                code = 429 if e.reason == "queue_full" else 503
                self._json(code, {"error": str(e), "reason": e.reason,
                                  "retry_after": e.retry_after,
                                  "trace_id": tid},
                           headers=hdr + [
                               ("Retry-After",
                                f"{max(e.retry_after, 0.01):.3f}")])
                return
            except Expired as e:
                self._json(504, {"error": str(e), "trace_id": tid},
                           headers=hdr)
                return
            except ServerClosed as e:
                self._json(503, {"error": str(e), "reason": "draining",
                                 "trace_id": tid}, headers=hdr)
                return
            except ServingError as e:
                self._json(500, {"error": str(e), "trace_id": tid},
                           headers=hdr)
                return
            except ValueError as e:       # ragged / non-numeric rows
                self._json(400, {"error": f"bad request: {e}"})
                return
            self._json(200, {"outputs": np.asarray(out).tolist(),
                             "trace_id": tid}, headers=hdr)

    class ReplicaHTTPServer(ThreadingHTTPServer):
        """ThreadingHTTPServer that tracks live connections so
        ``kill()`` can tear them mid-write — the in-process SIGKILL
        twin (testing/faults.py family (p), bench row
        ``fleet_failover``): clients see a reset/EOF, never a
        goodbye. EmbeddingShardServer.kill() is the RPC-plane
        precedent."""

        daemon_threads = True

        def __init__(self, addr, handler):
            super().__init__(addr, handler)
            self._conn_lock = named_lock("serving.httpd")
            self._conns = set()   # ptlint: guarded-by(serving.httpd)
            self._killed = False

        def get_request(self):
            sock, addr = super().get_request()
            with self._conn_lock:
                self._conns.add(sock)
            return sock, addr

        def shutdown_request(self, request):
            with self._conn_lock:
                self._conns.discard(request)
            super().shutdown_request(request)

        def handle_error(self, request, client_address):
            # torn sockets (kill(), client disconnects) are expected
            # under chaos — never traceback-spam the daemon's stderr
            import sys
            exc = sys.exc_info()[1]
            if isinstance(exc, (BrokenPipeError, ConnectionError,
                                OSError)):
                return
            super().handle_error(request, client_address)

        def kill(self) -> None:
            """Tear every live connection and stop the listener — no
            drain, no goodbye. Connections are torn FIRST (a SIGKILL
            is instant; the serve-loop handshake in shutdown() can
            take up to its poll interval, and a fast replica would
            finish streaming in that window). In-flight streaming
            handlers hit BrokenPipe on their next write; their
            clients see a torn (close-delimited, terminal-record-less)
            stream."""
            self._killed = True
            with self._conn_lock:
                conns = list(self._conns)
            for s in conns:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            self.shutdown()
            self.server_close()

    return ReplicaHTTPServer((host, port), Handler)
