"""Host-RAM spill tier for cold KV pages — the second tier of the
two-tier KV plane (docs/robustness.md "Two-tier KV cache").

Under pool pressure the engine used to FREE cold refcount-1 prefix
pages (``prefix.evict_lru``) — destroying exactly the warm prefixes
that made the trie valuable under exactly the load that exercises it.
With a :class:`SpillStore` attached, those pages are read back to host
RAM first (``PagedDecoder.read_page``), keyed by their full token path
from the trie root, and a later ``prefix.match()`` that walks to the
same path RESTORES the page into a freshly allocated device page
before prefill is charged (``DecodeEngine._restore_spilled``) —
capacity degrades to a host round-trip, never to a recompute.

Crash-safety is an ORDERING contract, enforced by the engine, not by
this store:

1. read the device page to host and checksum it (no state changed);
2. evict the trie node + free the device page (the page is GONE from
   tier 1 — a crash here loses cache contents, never accounting);
3. ``put()`` the complete, checksummed entry (the commit point).

A SIGKILL between any two steps leaves the accounting balanced: before
(2) the trie still owns the page, between (2) and (3) the page is
simply free and the store has no entry. There is no reachable state
where a page is BOTH device-owned and host-stored, so a restore can
never resurrect a page that was never freed. Torn writes (a crash or
bit-rot INSIDE the committed payload) are caught at restore time by
the per-entry CRC: the entry is dropped and journaled
(``engine/spill_integrity``) and the lookup degrades to a prefix miss
— a torn page is never restored.

The store is capacity-bounded (``kv_spill_pages``) with LRU eviction
among entries; all state is guarded by the named ``serving.spillstore``
lock (engine mutates from its stepping thread, stats() reads from
anywhere). Lock order: serving.engine -> serving.prefix ->
serving.spillstore, never the reverse.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.analysis.lockdep import named_lock

__all__ = ["SpillStore", "SpillEntry", "entry_checksum"]


def entry_checksum(payload: Dict[str, np.ndarray]) -> int:
    """CRC32 over every leaf's raw bytes, keyed in sorted order — the
    integrity witness a restore re-derives before trusting an entry."""
    crc = 0
    for name in sorted(payload):
        arr = payload[name]
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc


class SpillEntry:
    """One spilled page: the host copies of its pool leaves (flattened
    ``{leaf-name: np.ndarray}``) plus the CRC computed BEFORE the
    device page was freed. ``verify()`` re-derives the CRC — False
    means a torn write or corruption and the entry must be dropped."""

    __slots__ = ("payload", "crc", "nbytes")

    def __init__(self, payload: Dict[str, np.ndarray],
                 crc: Optional[int] = None):
        self.payload = payload
        self.crc = entry_checksum(payload) if crc is None else int(crc)
        self.nbytes = int(sum(a.nbytes for a in payload.values()))

    def verify(self) -> bool:
        try:
            return entry_checksum(self.payload) == self.crc
        except Exception:
            return False


class SpillStore:
    """LRU host-RAM store of spilled KV pages, keyed by the page's
    full token path (a tuple of ints — the trie path that produced
    it). Capacity is in PAGES; ``put`` beyond capacity drops the
    least-recently-touched entries (counted, not journaled — host
    eviction is lossy-cache behavior, not a fault)."""

    def __init__(self, capacity_pages: int):
        assert capacity_pages >= 1, capacity_pages
        self.capacity = int(capacity_pages)
        self._lock = named_lock("serving.spillstore")
        # token path -> SpillEntry  # ptlint: guarded-by(serving.spillstore)
        self._entries: "OrderedDict[tuple, SpillEntry]" = OrderedDict()
        self.put_count = 0
        self.restored_count = 0
        self.evicted_lru = 0
        self.dropped_integrity = 0
        self.high_water = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def bytes_used(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def has(self, key: tuple) -> bool:
        with self._lock:
            return tuple(key) in self._entries

    def put(self, key: tuple, entry: SpillEntry) -> List[tuple]:
        """Commit one spilled page (the LAST step of the spill
        ordering contract). Returns the keys LRU-dropped to stay
        within capacity."""
        key = tuple(key)
        dropped: List[tuple] = []
        with self._lock:
            if key in self._entries:
                del self._entries[key]
            self._entries[key] = entry
            self.put_count += 1
            self.high_water = max(self.high_water, len(self._entries))
            while len(self._entries) > self.capacity:
                old, _ = self._entries.popitem(last=False)
                self.evicted_lru += 1
                dropped.append(old)
        return dropped

    def pop(self, key: tuple) -> Optional[SpillEntry]:
        """Remove and return the entry for ``key`` (restore takes
        ownership — a failed restore must NOT re-insert a possibly
        torn entry)."""
        with self._lock:
            return self._entries.pop(tuple(key), None)

    def touch(self, key: tuple) -> None:
        with self._lock:
            key = tuple(key)
            if key in self._entries:
                self._entries.move_to_end(key)

    def clear(self) -> int:
        """Drop everything — the engine's step-failure recovery path,
        where the trie the keys were carved from no longer exists
        (never resurrect across a rebuild). Returns the drop count."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def accounting(self) -> dict:
        with self._lock:
            return {"spilled": len(self._entries),
                    "spill_capacity": self.capacity,
                    "spill_bytes": sum(e.nbytes
                                       for e in self._entries.values()),
                    "spill_puts": self.put_count,
                    "spill_restores": self.restored_count,
                    "spill_evicted_lru": self.evicted_lru,
                    "spill_dropped_integrity": self.dropped_integrity,
                    "spill_high_water": self.high_water}

    # test/chaos hook: corrupt one stored entry in place (bit-flip or
    # torn truncation) WITHOUT touching its recorded CRC — the restore
    # path must catch it (testing/faults.py corrupt_spilled_page)
    def corrupt_one(self, mode: str = "bitflip",
                    rng=None) -> Optional[tuple]:
        import random as _random
        rng = rng or _random
        with self._lock:
            if not self._entries:
                return None
            key = rng.choice(list(self._entries))
            entry = self._entries[key]
            name = sorted(entry.payload)[0]
            arr = np.array(entry.payload[name], copy=True)
            if mode == "truncate":
                flat = arr.reshape(-1)
                flat[flat.size // 2:] = 0
            else:
                bb = arr.view(np.uint8).reshape(-1)
                bb[rng.randrange(bb.size)] ^= 0x40
            entry.payload[name] = arr
            if entry.verify():
                # mutation was a no-op (e.g. an all-zero page under
                # truncation): force a delta so the integrity path
                # actually fires
                bb = arr.view(np.uint8).reshape(-1)
                bb[0] ^= 0xFF
            return key
