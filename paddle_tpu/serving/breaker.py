"""Sliding-window failure-rate circuit breaker for the serving path.

When the model itself is sick (poisoned weights, a hung device, an
artifact that faults every forward), queue backpressure is the wrong
tool: every admitted request burns a worker slot on a doomed forward.
The breaker watches the outcome of the last ``window`` forwards and,
when the failure fraction crosses ``failure_threshold`` (with at least
``min_requests`` observed), OPENS: every request is shed instantly with
a retry-after hint. After ``cooldown`` seconds it HALF-OPENS and admits
``half_open_probes`` probe requests; all probes succeeding CLOSES the
breaker (window cleared), any probe failing re-opens it for another
cooldown. Deterministic under test via the injectable ``clock``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Tuple

from paddle_tpu.analysis.lockdep import named_lock
from paddle_tpu.obs.events import emit as journal_emit

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, window: int = 64, failure_threshold: float = 0.5,
                 min_requests: int = 8, cooldown: float = 2.0,
                 half_open_probes: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        if not (0.0 < failure_threshold <= 1.0):
            raise ValueError("failure_threshold must be in (0, 1]")
        self.window = int(window)
        self.failure_threshold = float(failure_threshold)
        self.min_requests = max(1, int(min_requests))
        self.cooldown = float(cooldown)
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._lock = named_lock("serving.breaker")
        self._outcomes: deque = deque(maxlen=self.window)  # True = ok
        self._state = CLOSED
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self._probe_successes = 0
        self.trips = 0              # total closed->open transitions

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown:
            self._state = HALF_OPEN
            self._probes_in_flight = 0
            self._probe_successes = 0
            journal_emit("serving", "breaker", state=HALF_OPEN)

    def allow(self) -> Tuple[bool, float]:
        """(admit?, retry_after_seconds). retry_after is 0 when admitted
        and the remaining cooldown (or a probe-slot wait) when shed."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True, 0.0
            if self._state == OPEN:
                remaining = self.cooldown - (self._clock() -
                                             self._opened_at)
                return False, max(remaining, 0.0)
            # HALF_OPEN: admit only the probe budget
            if self._probes_in_flight < self.half_open_probes:
                self._probes_in_flight += 1
                return True, 0.0
            return False, max(self.cooldown / 4.0, 0.01)

    def record(self, ok: bool) -> None:
        """Outcome of an admitted request's forward."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probes_in_flight = max(0,
                                             self._probes_in_flight - 1)
                if not ok:
                    self._state = OPEN
                    self._opened_at = self._clock()
                    journal_emit("serving", "breaker", state=OPEN,
                                 probe_failed=True)
                    return
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self._state = CLOSED
                    self._outcomes.clear()
                    journal_emit("serving", "breaker", state=CLOSED)
                return
            if self._state == OPEN:
                return          # stragglers admitted before the trip
            self._outcomes.append(bool(ok))
            if len(self._outcomes) < self.min_requests:
                return
            failures = self._outcomes.count(False)
            if failures / len(self._outcomes) >= self.failure_threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1
                journal_emit(
                    "serving", "breaker", state=OPEN, trips=self.trips,
                    failure_rate=failures / len(self._outcomes))

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            n = len(self._outcomes)
            failures = self._outcomes.count(False)
            return {
                "state": self._state,
                "window": n,
                "failure_rate": (failures / n) if n else 0.0,
                "trips": self.trips,
                "cooldown": self.cooldown,
            }
