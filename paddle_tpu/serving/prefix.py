"""Shared-prefix KV reuse: a page-granularity radix index over the
paged pool (the vLLM/SGLang prefix-cache design on the PR-6 engine).

Every request that finishes (or is evicted) leaves its COMPLETE KV
pages behind in a trie keyed by page-size token runs: node ``(t_0..t_{ps-1})
-> (t_ps..)`` holds the physical page whose rows are the teacher-forced
K/V of exactly those tokens at exactly those positions. A later request
whose prompt walks the same token path ATTACHES those pages instead of
recomputing them — admission charges only the *novel* pages, and the
attached prefill steps disappear (the ``decode_prefix_hit`` bench row
measures warm vs. cold TTFT).

Correctness leans on two invariants:

- KV is a pure function of (token run, positions): pages are only
  inserted for fully teacher-forced token runs starting at position 0,
  so an attached page is bit-identical to what the slot would have
  written itself — greedy token-identity is preserved by construction
  (pinned in tests/test_paged_decode.py).
- Attached pages are never written: a slot admitted with ``matched``
  tokens starts scattering at position ``matched``, which lands in its
  first PRIVATE page. Divergence INSIDE a page is handled by
  copy-on-write: the shared page is device-copied into a fresh page
  (PagedDecoder.copy_page — one compile, traced src/dst) and the match
  extends to the common rows of the copy.

Ownership is reference counting in :class:`~paddle_tpu.serving.engine.PagePool`:
the trie holds ONE ref per indexed page, every slot using it holds
another; ``free()`` only returns a page to the free list at refcount
zero, and ``page_accounting()`` extends the zero-leak invariant to
``refs_total == held_by_slots + held_by_trie`` (the chaos suite's
zero-underflow assertion — tests/test_serving_faults.py family (n)).

Under pool pressure the engine reclaims least-recently-used LEAF nodes
(refcount 1 — trie-only) BEFORE preempting a running slot, journaled as
``engine/prefix_evict``. All trie state is guarded by the named
``serving.prefix`` InstrumentedLock (analysis/lockdep.py): mutation
happens on the engine's stepping thread, but stats()/flight providers
read from arbitrary threads. Lock order is engine -> prefix -> pagepool
(never the reverse), witnessed by the autouse lockdep fixture.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from paddle_tpu.analysis.lockdep import named_lock

__all__ = ["PrefixIndex", "PrefixMatch"]


class _Node:
    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key, page, parent):
        self.key = key                   # page_size token tuple
        self.page = page                 # physical page id (None: root)
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.last_used = 0


class PrefixMatch:
    """One lookup's result: ``pages`` are fully-shared physical pages
    (in logical order), ``matched`` counts their tokens, and ``cow``
    (when set) is ``(physical_page, rows)`` — the best partially-
    matching child page whose first ``rows`` tokens agree, a
    copy-on-write candidate."""

    __slots__ = ("pages", "matched", "cow")

    def __init__(self, pages: List[int], matched: int,
                 cow: Optional[Tuple[int, int]]):
        self.pages = pages
        self.matched = matched
        self.cow = cow


class PrefixIndex:
    """Radix/trie index of shared KV pages (see module doc). All
    public methods take the named ``serving.prefix`` lock; the engine
    calls the mutators from its stepping thread only."""

    def __init__(self, pool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        self._lock = named_lock("serving.prefix")
        # the radix trie + LRU clock  # ptlint: guarded-by(serving.prefix)
        self._root = _Node(None, None, None)
        self._seq = 0                  # ptlint: guarded-by(serving.prefix)
        self._nodes = 0                # ptlint: guarded-by(serving.prefix)
        self.hit_pages = 0
        self.miss_pages = 0
        self.cow_hits = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # --------------------------------------------------------------- lookup
    def match(self, tokens) -> PrefixMatch:
        """Longest shared-page walk for ``tokens`` (prompt + replayed
        generation). The match is capped at ``len(tokens) - 1`` so the
        slot always has at least one token left to feed — the step
        needs a query to produce the next token."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        limit = len(toks) - 1
        pages: List[int] = []
        cow = None
        with self._lock:
            node = self._root
            i = 0
            while i + ps <= limit:
                child = node.children.get(tuple(toks[i:i + ps]))
                if child is None:
                    break
                self._seq += 1
                child.last_used = self._seq
                pages.append(child.page)
                node = child
                i += ps
            # partial-page (copy-on-write) candidate: the child sharing
            # the longest leading token run inside the next page
            best = 0
            best_child = None
            remaining = toks[i:]
            for key, child in node.children.items():
                j = 0
                cap = min(ps, limit - i)
                while j < cap and key[j] == remaining[j]:
                    j += 1
                if j > best:
                    best = j
                    best_child = child
                    cow = (child.page, j)
            if best_child is not None:
                self._seq += 1
                best_child.last_used = self._seq
        return PrefixMatch(pages, i, cow)

    # -------------------------------------------------------------- insert
    def insert(self, tokens, pages: List[int]) -> int:
        """Register the COMPLETE pages of a finished/evicted slot's
        teacher-forced token run. ``pages`` is the slot's physical page
        list (logical order). Existing nodes are touched, novel ones
        take one pool ref each. Returns the number of new nodes."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        new = 0
        with self._lock:
            node = self._root
            i = 0
            while i + ps <= len(toks) and i // ps < len(pages):
                key = tuple(toks[i:i + ps])
                child = node.children.get(key)
                if child is None:
                    page = pages[i // ps]
                    self.pool.ref(page)
                    child = _Node(key, page, node)
                    node.children[key] = child
                    self._nodes += 1
                    new += 1
                self._seq += 1
                child.last_used = self._seq
                node = child
                i += ps
            self.inserted_pages += new
        return new

    # ------------------------------------------------------------ eviction
    def evict_lru(self, n: int = 1) -> List[int]:
        """Free up to ``n`` least-recently-used LEAF pages whose only
        owner is the trie (pool refcount 1). Returns the freed physical
        pages — inner nodes become leaves as their children go, so a
        caller looping this reclaims whole cold branches."""
        freed: List[int] = []
        with self._lock:
            while len(freed) < n:
                victim = None
                stack = [self._root]
                while stack:
                    nd = stack.pop()
                    if nd.page is not None and not nd.children and \
                            self.pool.refcount(nd.page) == 1:
                        if victim is None or \
                                nd.last_used < victim.last_used:
                            victim = nd
                    stack.extend(nd.children.values())
                if victim is None:
                    break
                del victim.parent.children[victim.key]
                self._nodes -= 1
                self.pool.free([victim.page])
                freed.append(victim.page)
            self.evicted_pages += len(freed)
        return freed

    # --------------------------------------------------------------- spill
    @staticmethod
    def _path_of(node: _Node) -> tuple:
        """Full token path from the root through ``node`` — the spill
        store's key (serving/spill.py): restores look the SAME token
        run back up, so the key must be reconstructable from the
        request's replay alone."""
        keys = []
        while node.key is not None:
            keys.append(node.key)
            node = node.parent
        out = []
        for key in reversed(keys):
            out.extend(key)
        return tuple(out)

    def spill_candidates(self, n: int = 1) -> List[Tuple[tuple, int]]:
        """Up to ``n`` least-recently-used LEAF pages whose only owner
        is the trie, as ``(token_path, physical_page)`` — NO mutation.
        The engine spills these device->host and then calls
        :meth:`evict_exact` per page, keeping the crash-safety
        ordering (read, evict+free, commit) under ITS control."""
        with self._lock:
            leaves = []
            stack = [self._root]
            while stack:
                nd = stack.pop()
                if nd.page is not None and not nd.children and \
                        self.pool.refcount(nd.page) == 1:
                    leaves.append(nd)
                stack.extend(nd.children.values())
            leaves.sort(key=lambda nd: nd.last_used)
            return [(self._path_of(nd), nd.page) for nd in leaves[:n]]

    def evict_exact(self, path: tuple) -> Optional[int]:
        """Remove the node at exactly ``path`` (a full token path) and
        free its page — the evict+free step of the spill ordering. The
        node must still be a trie-only (refcount 1) childless leaf;
        returns the freed page, or None if the node changed since
        :meth:`spill_candidates` picked it (grew children, gained a
        slot ref, vanished) — the caller then simply skips the spill."""
        ps = self.page_size
        path = tuple(int(t) for t in path)
        if not path or len(path) % ps != 0:
            return None
        with self._lock:
            node = self._root
            for i in range(0, len(path), ps):
                node = node.children.get(path[i:i + ps])
                if node is None:
                    return None
            if node.children or self.pool.refcount(node.page) != 1:
                return None
            page = node.page
            del node.parent.children[node.key]
            self._nodes -= 1
            self.pool.free([page])
            self.evicted_pages += 1
            return page

    def reclaimable_pages(self) -> int:
        """Pages an eviction loop could eventually return to the free
        list: trie pages no slot is also holding (refcount 1)."""
        with self._lock:
            count = 0
            stack = [self._root]
            while stack:
                nd = stack.pop()
                if nd.page is not None and \
                        self.pool.refcount(nd.page) == 1:
                    count += 1
                stack.extend(nd.children.values())
            return count

    # ------------------------------------------------------------ lifecycle
    def flush(self) -> int:
        """Drop the whole index, returning every trie ref to the pool
        (shared pages stay allocated for the slots still holding them).
        Returns the number of dropped nodes."""
        with self._lock:
            dropped = self._collect_pages()
            for page in dropped:
                self.pool.free([page])
            n = self._nodes
            self._root = _Node(None, None, None)
            self._nodes = 0
        return n

    def reset(self) -> None:
        """Forget every node WITHOUT touching the pool — the step-
        failure recovery path, where the engine has already rebuilt the
        PagePool from scratch."""
        with self._lock:
            self._root = _Node(None, None, None)
            self._nodes = 0

    def _collect_pages(self) -> List[int]:
        pages = []
        stack = [self._root]
        while stack:
            nd = stack.pop()
            if nd.page is not None:
                pages.append(nd.page)
            stack.extend(nd.children.values())
        return pages

    # ------------------------------------------------------------ snapshots
    def page_count(self) -> int:
        with self._lock:
            return self._nodes

    def summary(self) -> dict:
        """Flight-bundle / stats() view: trie shape + cumulative hit
        accounting + the pool's refcount histogram."""
        with self._lock:
            depth = 0
            stack = [(self._root, 0)]
            while stack:
                nd, d = stack.pop()
                depth = max(depth, d)
                stack.extend((c, d + 1) for c in nd.children.values())
            return {
                "nodes": self._nodes,
                "pages": self._nodes,
                "max_depth_pages": depth,
                "hit_pages": self.hit_pages,
                "miss_pages": self.miss_pages,
                "cow_copies": self.cow_hits,
                "inserted_pages": self.inserted_pages,
                "evicted_pages": self.evicted_pages,
                "refcount_histogram": self.pool.refcount_histogram(),
            }
