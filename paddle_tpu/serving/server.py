"""InferenceServer — admission-controlled serving over a merged model.

The reference C API existed so a fleet of C threads could serve a shared
model (`paddle_gradient_machine_create_shared_param`); what it never had
was admission control — overload meant unbounded queues and timeouts
meant dead clients. This wraps ``load_inference_model`` with:

- a BOUNDED request queue with backpressure: a full queue rejects
  instantly with a retry-after hint instead of buffering unboundedly
  (``Rejected``, reason ``queue_full``);
- per-request DEADLINES enforced around the jitted forward: a request
  that expires while queued is never run; one whose forward finishes
  past its deadline is counted ``expired`` and its result discarded;
- a sliding-window failure-rate CIRCUIT BREAKER (serving/breaker.py)
  that sheds load while the model is sick and half-opens on a cooldown
  (``Rejected``, reason ``breaker_open``);
- memory-pressure shedding (docs/robustness.md "Memory pressure"): a
  forward that dies with XLA ``RESOURCE_EXHAUSTED`` is a CAPACITY
  fault, not a model fault — the request is shed with ``Rejected``
  (reason ``resource_exhausted``, retry-after hint), the adaptive
  max-batch-rows limit halves so the next oversized request is
  rejected at ADMISSION instead of wasting a device dispatch, and the
  circuit breaker is NOT fed (the model isn't poisoned — the batch was
  too big). ``max_batch_memory`` adds a static admission budget: the
  request's estimated device bytes must fit it;
- graceful DRAIN on shutdown: no new admissions, queued work completes;
- ``health()`` / ``stats()`` snapshots — queue depth, p50/p99 latency,
  served/rejected/expired/failed counters — with every forward timed
  through ``utils/stats.py`` (``serving/forward`` in global_stat).

See docs/robustness.md "Serving" and tests/test_serving_faults.py (the
chaos suite driving hung forwards, poisoned requests, bursts and
mid-request destroys against this class).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Union

import numpy as np

from paddle_tpu.obs import context as obs_context
from paddle_tpu.analysis.lockdep import named_condition
from paddle_tpu.obs.events import emit as journal_emit
from paddle_tpu.obs.flight import FLIGHT
from paddle_tpu.serving.breaker import CircuitBreaker
from paddle_tpu.utils.stats import global_counters, stat_timer


def _estimate_nbytes(samples) -> int:
    """Rough device footprint of a request: the summed nbytes of its
    sample fields (activation memory scales with it). Estimation only —
    the authoritative signal stays the allocator's RESOURCE_EXHAUSTED."""
    total = 0
    for sample in samples:
        fields = sample if isinstance(sample, (tuple, list)) else (sample,)
        for f in fields:
            arr = np.asarray(f)
            total += arr.nbytes if arr.dtype != object else 8 * arr.size
    return total


class ServingError(RuntimeError):
    """Base of every typed serving failure."""


class Rejected(ServingError):
    """Shed at admission. ``retry_after`` (seconds) is the client hint;
    ``reason`` is 'queue_full' or 'breaker_open'."""

    def __init__(self, msg: str, retry_after: float, reason: str):
        super().__init__(msg)
        self.retry_after = float(retry_after)
        self.reason = reason


class Expired(ServingError):
    """The request's deadline passed (queued too long, or the forward
    ran past it)."""


class ServerClosed(ServingError):
    """Submitted to a draining or stopped server."""


class _Request:
    __slots__ = ("samples", "deadline", "done", "result", "error",
                 "enqueued_at", "trace_id", "_settled")

    def __init__(self, samples, deadline: Optional[float], now: float,
                 trace_id: Optional[str] = None):
        self.samples = samples
        self.deadline = deadline
        self.done = threading.Event()
        self.result = None
        self.error: Optional[ServingError] = None
        self.enqueued_at = now
        # one id end-to-end: admission -> queue wait -> forward ->
        # settle all stamp it (docs/observability.md "Trace context")
        self.trace_id = trace_id or obs_context.new_trace_id()
        self._settled = False

    def get(self, timeout: Optional[float] = None):
        """Block for the result; raises the typed error on failure. With
        a deadline, waits only slightly past it — a hung forward cannot
        hang the CLIENT, only the worker slot (the breaker then opens)."""
        if timeout is None and self.deadline is not None:
            timeout = max(self.deadline - time.monotonic(), 0.0) + 0.25
        if not self.done.wait(timeout):
            raise Expired("request still in flight past its deadline")
        if self.error is not None:
            raise self.error
        return self.result


class InferenceServer:
    """Admission-controlled, breaker-protected serving facade.

    ``model`` is a merged-artifact path (load_inference_model) or a
    ready ``Inference``. ``workers`` threads pull from the bounded
    queue; ``default_deadline`` (seconds) applies when submit() passes
    none. ``breaker=None`` installs a default CircuitBreaker; pass an
    instance to tune it, or ``breaker=False`` to disable shedding."""

    def __init__(self, model, *, max_queue: int = 64, workers: int = 1,
                 default_deadline: Optional[float] = None,
                 breaker: Union[CircuitBreaker, None, bool] = None,
                 latency_window: int = 256,
                 max_batch_memory: Optional[int] = None,
                 engine=None,
                 sample_log: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        if isinstance(model, (str, bytes)):
            from paddle_tpu.trainer.inference import load_inference_model
            model = load_inference_model(model)
        self._inf = model
        # online-training feedback seam (paddle_tpu/embed/online.py
        # serving_sample_log): called with each served batch's samples
        # from the worker thread, after a successful forward
        self._sample_log = sample_log
        # optional continuous-batching decode engine
        # (serving/engine.DecodeEngine): generate() routes through its
        # page-aware admission — requests are scheduled by FREE KV
        # PAGES, not queue depth — and stats()/metrics export its
        # KV-page/slot gauges. start()/shutdown() manage its loop
        # thread alongside the forward workers.
        self.engine = engine
        self.max_queue = int(max_queue)
        self.num_workers = max(1, int(workers))
        self.default_deadline = default_deadline
        if breaker is None:
            breaker = CircuitBreaker()
        self.breaker: Optional[CircuitBreaker] = breaker or None
        # memory-pressure admission (docs/robustness.md "Memory
        # pressure"): a static bytes budget per request, plus an
        # adaptive row limit that HALVES each time a forward dies with
        # RESOURCE_EXHAUSTED — oversized requests then shed at
        # admission instead of wasting a device dispatch
        self.max_batch_memory = (int(max_batch_memory)
                                 if max_batch_memory else None)
        self._batch_limit: Optional[int] = None
        self._clock = clock
        self._cv = named_condition("serving.server")
        self._queue: deque = deque()  # ptlint: guarded-by(serving.server)
        self._threads: List[threading.Thread] = []
        self._accepting = False
        self._stopping = False
        self._inflight = 0
        self._latencies: deque = deque(maxlen=int(latency_window))
        self._started_at = None
        self._counters = {"served": 0, "rejected_full": 0,
                          "rejected_breaker": 0, "rejected_oom": 0,
                          "oom_events": 0, "expired": 0,
                          "failed": 0, "closed": 0}
        # live-state provider for postmortem bundles: what was queued
        # (by trace_id) when the dump fired. Weakref'd so an abandoned
        # server never pins itself in the recorder.
        import weakref
        ref = weakref.ref(self)

        def _flight_state():
            srv = ref()
            if srv is None:
                return None
            with srv._cv:
                return {"queued_trace_ids":
                        [r.trace_id for r in srv._queue],
                        "inflight": srv._inflight,
                        "accepting": srv._accepting,
                        "batch_limit": srv._batch_limit}

        FLIGHT.register_state_provider(f"serving-{id(self):x}",
                                       _flight_state)

        # SLO-watchdog source (obs/slo.py): the server's stats() plus a
        # derived shed_rate, so declarative objectives like
        # "shed_rate<=0.05" or "p99_ms<=250" evaluate over live numbers
        def _slo_stats():
            srv = ref()
            if srv is None:
                return None
            s = srv.stats()
            shed = (s.get("rejected_full", 0)
                    + s.get("rejected_breaker", 0)
                    + s.get("rejected_oom", 0))
            total = shed + s.get("served", 0)
            s["shed_rate"] = shed / total if total else 0.0
            return s

        from paddle_tpu.obs.slo import WATCHDOG
        WATCHDOG.add_source(f"serving-{id(self):x}", _slo_stats)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InferenceServer":
        with self._cv:
            if self._threads:
                return self
            self._accepting = True
            self._stopping = False
            self._started_at = self._clock()
            for i in range(self.num_workers):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"pt-serve-worker-{i}",
                                     daemon=True)
                t.start()
                self._threads.append(t)
        if self.engine is not None:
            self.engine.start()
        return self

    def drain(self) -> dict:
        """Deploy-drain (docs/robustness.md "Serving fleet"): stop
        ADMITTING — submit/submit_generate raise ServerClosed, the
        HTTP front answers 503 reason "draining" — while workers and
        the engine keep settling everything already admitted and the
        transport stays up. The fleet router's POST /admin/drain leg;
        reversible via :meth:`resume`, unlike :meth:`shutdown`."""
        with self._cv:
            self._accepting = False
        if self.engine is not None:
            self.engine.drain_admission()
        journal_emit("serving", "drain", action="drain")
        return self.health()

    def resume(self) -> dict:
        """Re-open admission after :meth:`drain` (re-admit on deploy
        completion / rejoin). No-op on a stopped server."""
        with self._cv:
            if self._threads and not self._stopping:
                self._accepting = True
        if self.engine is not None:
            self.engine.resume_admission()
        journal_emit("serving", "drain", action="resume")
        return self.health()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 30.0) -> None:
        """Stop accepting. With ``drain`` the queued requests complete
        first; without it they fail with ServerClosed immediately."""
        if self.engine is not None:
            self.engine.shutdown(drain=drain, timeout=timeout)
        with self._cv:
            self._accepting = False
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._settle(req, error=ServerClosed(
                        "server shut down before this request ran"))
                    self._counters["closed"] += 1
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        with self._cv:
            self._threads = []

    # ------------------------------------------------------------ admission
    def submit(self, samples, deadline: Optional[float] = None,
               trace_id: Optional[str] = None) -> _Request:
        """Admit one request (a list of sample tuples, as
        Inference.infer takes). Returns a future-like _Request. Raises
        Rejected/ServerClosed at admission; the request itself settles
        with a result or a typed error. ``trace_id`` correlates the
        request end-to-end (minted here when the transport passed
        none); every shed/settle record carries it."""
        now = self._clock()
        trace_id = trace_id or obs_context.current().trace_id \
            or obs_context.new_trace_id()
        if deadline is None:
            deadline = self.default_deadline
        abs_deadline = (time.monotonic() + deadline) \
            if deadline is not None else None
        with self._cv:
            if not self._accepting:
                raise ServerClosed("server is draining or stopped")
            rows = len(samples) if hasattr(samples, "__len__") else None
            if rows is not None:
                if self._batch_limit is not None and \
                        rows > self._batch_limit:
                    self._counters["rejected_oom"] += 1
                    journal_emit("serving", "shed",
                                 reason="resource_exhausted",
                                 where="admission_rows", rows=rows,
                                 limit=self._batch_limit,
                                 trace_id=trace_id)
                    raise Rejected(
                        f"batch of {rows} rows exceeds the adaptive "
                        f"limit of {self._batch_limit} (a previous "
                        "forward hit RESOURCE_EXHAUSTED at that size); "
                        "split the request",
                        retry_after=self._retry_hint(),
                        reason="resource_exhausted")
                if self.max_batch_memory is not None:
                    est = _estimate_nbytes(samples)
                    if est > self.max_batch_memory:
                        self._counters["rejected_oom"] += 1
                        journal_emit("serving", "shed",
                                     reason="resource_exhausted",
                                     where="admission_bytes",
                                     estimated_bytes=est,
                                     budget=self.max_batch_memory,
                                     trace_id=trace_id)
                        raise Rejected(
                            f"request estimated at {est} bytes exceeds "
                            f"max_batch_memory={self.max_batch_memory}; "
                            "split the request",
                            retry_after=self._retry_hint(),
                            reason="resource_exhausted")
            if self.breaker is not None:
                ok, retry = self.breaker.allow()
                if not ok:
                    self._counters["rejected_breaker"] += 1
                    journal_emit("serving", "shed",
                                 reason="breaker_open",
                                 retry_after=retry,
                                 trace_id=trace_id)
                    raise Rejected(
                        f"circuit breaker open; retry in {retry:.2f}s",
                        retry_after=retry, reason="breaker_open")
            if len(self._queue) >= self.max_queue:
                self._counters["rejected_full"] += 1
                retry = self._retry_hint()
                journal_emit("serving", "shed", reason="queue_full",
                             queue_depth=len(self._queue),
                             retry_after=retry, trace_id=trace_id)
                raise Rejected(
                    f"queue full ({self.max_queue}); retry in "
                    f"{retry:.2f}s", retry_after=retry,
                    reason="queue_full")
            req = _Request(samples, abs_deadline, now,
                           trace_id=trace_id)
            depth = len(self._queue)
            self._queue.append(req)
            self._cv.notify()
        FLIGHT.record("mark", "serving/admit", trace_id=trace_id,
                      queue_depth=depth)
        return req

    def infer(self, samples, deadline: Optional[float] = None,
              trace_id: Optional[str] = None):
        """Synchronous submit + wait."""
        return self.submit(samples, deadline, trace_id=trace_id).get()

    # --------------------------------------------------------- generation
    def submit_generate(self, prompt, max_new_tokens: int, *,
                        eos_id: Optional[int] = None,
                        deadline: Optional[float] = None,
                        trace_id: Optional[str] = None):
        """Admit one generation request into the continuous-batching
        decode engine (requires ``engine=``). Admission is the ENGINE's
        — scheduled by free KV pages, with the same typed errors as
        ``submit`` (``Rejected`` reasons ``kv_capacity``/``queue_full``,
        ``ServerClosed`` when draining). Returns the engine's
        future-like GenRequest (``.get()`` / ``.cancel()``)."""
        if self.engine is None:
            raise ServingError(
                "no decode engine attached — construct the server "
                "with engine=DecodeEngine(...)")
        if deadline is None:
            deadline = self.default_deadline
        return self.engine.submit(prompt, max_new_tokens,
                                  eos_id=eos_id, deadline=deadline,
                                  trace_id=trace_id)

    def generate(self, prompt, max_new_tokens: int, *,
                 eos_id: Optional[int] = None,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None):
        """Synchronous submit_generate + wait -> generated token ids."""
        return self.submit_generate(prompt, max_new_tokens,
                                    eos_id=eos_id,
                                    deadline=deadline,
                                    trace_id=trace_id).get()

    def _retry_hint(self) -> float:
        lats = list(self._latencies)
        per = (sum(lats) / len(lats)) if lats else 0.05
        return max(per * (len(self._queue) + 1) / self.num_workers, 0.01)

    # ------------------------------------------------------------- workers
    def _settle(self, req: _Request, result=None,
                error: Optional[ServingError] = None) -> bool:
        """Deliver exactly once (caller may have timed out and gone)."""
        if req._settled:
            return False
        req._settled = True
        req.result = result
        req.error = error
        req.done.set()
        return True

    def _worker_loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait(0.2)
                if not self._queue:
                    if self._stopping:
                        return
                    continue
                req = self._queue.popleft()
                self._inflight += 1
            try:
                self._serve_one(req)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _serve_one(self, req: _Request):
        now = time.monotonic()
        # the queue wait is part of the request's trace: how long
        # admission-to-dequeue took, by trace_id
        FLIGHT.record("mark", "serving/queue_wait",
                      trace_id=req.trace_id,
                      wait_s=round(now - req.enqueued_at, 6))
        if req.deadline is not None and now > req.deadline:
            # expired while queued: never runs. Pure overload — handled
            # by backpressure, so it does NOT feed the breaker.
            with self._cv:
                self._counters["expired"] += 1
            FLIGHT.record("mark", "serving/settle",
                          trace_id=req.trace_id, outcome="expired",
                          where="queued")
            self._settle(req, error=Expired(
                "deadline passed while queued"))
            return
        t0 = time.perf_counter()
        try:
            # the worker thread re-binds the request's trace context so
            # the forward span (and anything it journals) carries the id
            with obs_context.bind(trace_id=req.trace_id):
                with stat_timer("serving/forward"):
                    result = self._forward(req.samples)
        except Exception as e:
            from paddle_tpu.trainer.memory import is_resource_exhausted
            if is_resource_exhausted(e):
                # capacity fault, not a model fault: shed with a retry
                # hint, shrink the admission limit so the next oversized
                # request never reaches the device, and do NOT feed the
                # breaker (the model isn't poisoned — the batch was too
                # big for device memory)
                rows = len(req.samples) \
                    if hasattr(req.samples, "__len__") else 2
                with self._cv:
                    self._counters["oom_events"] += 1
                    cap = max(1, rows // 2)
                    self._batch_limit = cap if self._batch_limit is None \
                        else min(self._batch_limit, cap)
                    retry = self._retry_hint()
                global_counters.bump("serving/oom_events")
                journal_emit("serving", "shed",
                             reason="resource_exhausted",
                             where="forward", rows=rows,
                             new_batch_limit=cap,
                             trace_id=req.trace_id)
                self._settle(req, error=Rejected(
                    f"forward hit RESOURCE_EXHAUSTED on {rows} rows; "
                    f"max batch shrunk to {cap} — split the request "
                    f"and retry in {retry:.2f}s",
                    retry_after=retry, reason="resource_exhausted"))
                return
            with self._cv:
                self._counters["failed"] += 1
            if self.breaker is not None:
                self.breaker.record(False)
            FLIGHT.record("mark", "serving/settle",
                          trace_id=req.trace_id, outcome="failed",
                          error=repr(e)[:200])
            self._settle(req, error=ServingError(f"forward failed: {e}"))
            return
        dt = time.perf_counter() - t0
        with self._cv:
            self._latencies.append(dt)
        if req.deadline is not None and time.monotonic() > req.deadline:
            # ran, but too slowly: the deadline is enforced AROUND the
            # jitted forward. A slow/hung model is a model fault — it
            # feeds the breaker so sustained hangs shed load.
            with self._cv:
                self._counters["expired"] += 1
            if self.breaker is not None:
                self.breaker.record(False)
            self._settle(req, error=Expired(
                f"forward took {dt * 1e3:.0f}ms, past the deadline"))
            return
        if self.breaker is not None:
            self.breaker.record(True)
        FLIGHT.record("mark", "serving/settle",
                      trace_id=req.trace_id, outcome="served",
                      forward_ms=round(dt * 1e3, 3))
        self._settle(req, result=result)
        with self._cv:
            self._counters["served"] += 1

    def _forward(self, samples):
        out = self._inf.forward_batch(samples)
        if self._sample_log is not None:
            try:
                self._sample_log(samples)
            except Exception:  # noqa: BLE001 — a feedback-journal bug
                pass           # must never fail the serving request
        return out[0] if len(out) == 1 else out

    # ------------------------------------------------------------ snapshots
    def _percentile(self, lats: List[float], q: float) -> float:
        if not lats:
            return 0.0
        s = sorted(lats)
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def health(self) -> dict:
        with self._cv:
            running = bool(self._threads)
            accepting = self._accepting
            depth = len(self._queue)
        bstate = self.breaker.state if self.breaker is not None \
            else "disabled"
        if not running:
            status = "stopped"
        elif not accepting:
            status = "draining"
        elif bstate == "open":
            status = "shedding"
        else:
            status = "ok"
        return {"status": status, "accepting": accepting,
                "queue_depth": depth, "workers": self.num_workers,
                "breaker": bstate}

    def stats(self) -> dict:
        with self._cv:
            counters = dict(self._counters)
            depth = len(self._queue)
            inflight = self._inflight
            lats = list(self._latencies)
            uptime = (self._clock() - self._started_at) \
                if self._started_at is not None else 0.0
        out = dict(counters)
        out.update({
            "queue_depth": depth,
            "inflight": inflight,
            "batch_limit": self._batch_limit,
            "p50_ms": round(self._percentile(lats, 0.50) * 1e3, 3),
            "p99_ms": round(self._percentile(lats, 0.99) * 1e3, 3),
            "uptime_s": round(uptime, 3),
            "breaker": self.breaker.snapshot()
            if self.breaker is not None else None,
        })
        if self.engine is not None:
            out["engine"] = self.engine.stats()
        return out

    # convenience for HTTP clients sending raw dense rows
    def infer_rows(self, rows, deadline: Optional[float] = None,
                   trace_id: Optional[str] = None):
        samples = [(np.asarray(r, np.float32),) for r in rows]
        out = self.infer(samples, deadline, trace_id=trace_id)
        return np.asarray(out)
