"""Global process configuration.

Replaces the reference's gflags tier (paddle/utils/Flags.cpp:18-81 — ~40
process flags like use_gpu, trainer_count, ports, trainer_id) with a single
typed config object. Device selection is `use_tpu` beside the reference's
`use_gpu`; on a machine without TPUs JAX's CPU backend plays the role the
reference's CPU-only build (paddle/cuda/include/stub/*) played: the universal
fake device every test can run on.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class GlobalConfig:
    # Device policy (reference: use_gpu flag, Flags.cpp:18)
    use_tpu: bool = False
    # Data-parallel width; reference: trainer_count (Flags.cpp:23). 0 = all devices.
    trainer_count: int = 1
    # Reference: trainer_id / num_gradient_servers for multi-host (Flags.cpp:55-60).
    process_index: int = 0
    process_count: int = 1
    # Numeric policy: parameters are kept f32; matmul/conv compute dtype.
    compute_dtype: str = "float32"
    # Reference: log_period (Flags.cpp:33)
    log_period: int = 100
    # Reference: seed flag for deterministic runs
    seed: int = 0
    # FPE-trap equivalent (TrainerMain.cpp:49): raise at the first NaN.
    debug_nans: bool = False
    # Pallas flash attention for tile-friendly shapes on TPU
    use_flash_attention: bool = True
    initialized: bool = False


_g = GlobalConfig()


def init(use_tpu: Optional[bool] = None, use_gpu: Optional[bool] = None,
         trainer_count: int = 1, seed: int = 0, compute_dtype: str = "float32",
         log_period: int = 100, debug_nans: bool = False,
         **kwargs) -> GlobalConfig:
    """Initialize the framework. Mirrors paddle.v2.init(use_gpu=..., trainer_count=...).

    `use_gpu` is accepted for source compatibility with v2 scripts and treated
    as a request for the accelerator backend (i.e. the TPU here).

    `debug_nans=True` is the FPE-trap discipline of the reference trainer
    (TrainerMain.cpp:49 feenableexcept(FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW)):
    XLA re-runs any computation that produced a NaN un-jitted and raises at
    the exact primitive (jax_debug_nans), so a diverging run fails loudly at
    the source instead of training on garbage.
    """
    import jax

    # set AND clear: a later init(debug_nans=False) must un-latch the flag
    jax.config.update("jax_debug_nans", bool(debug_nans))
    _g.debug_nans = debug_nans
    if use_tpu is None:
        use_tpu = bool(use_gpu) if use_gpu is not None else None
    if use_tpu is None:
        use_tpu = jax.default_backend() == "tpu"
    _g.use_tpu = use_tpu
    _g.trainer_count = trainer_count if trainer_count > 0 else jax.local_device_count()
    _g.seed = seed
    _g.compute_dtype = compute_dtype
    _g.log_period = log_period
    _g.process_index = jax.process_index()
    _g.process_count = jax.process_count()
    _g.initialized = True
    return _g


def global_config() -> GlobalConfig:
    return _g
