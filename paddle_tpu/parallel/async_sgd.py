"""Asynchronous data parallelism, TPU-native form: local SGD islands.

Reference: the async-SGD path — `ParameterServer2::asyncSGD`
(paddle/pserver/ParameterServer2.cpp:457) and the Go pserver's
barrier-free `SendGrad` (go/pserver/service.go:221) — lets each trainer
push gradients and fetch parameters WITHOUT waiting for its peers, with
`max_async_count` bounding staleness. The payoff is straggler tolerance;
the price is stale gradients.

On TPU the intra-slice case is moot: the synchronous in-program
all-reduce over ICI is faster than any parameter-server hop, so "async
within a slice" would be a de-optimization. The case that survives is
ACROSS loosely-coupled workers (separate hosts/processes over DCN,
preemptible pools): there, the modern equivalent of async SGD is
**local SGD** — every island steps independently on its own shard
(parameters allowed to drift = bounded staleness), and islands
periodically reconcile by averaging parameters instead of streaming
per-step gradients through a server. Same tolerance property, no server,
and the reconciliation is one collective.

Two surfaces:

- `average_pytree(tree)` — cross-PROCESS parameter averaging (the
  reconciliation collective), built on multihost allgather; identity in
  single-process runs.
- `AsyncSGDIsland(trainer, sync_period)` — wraps an SGD trainer; its
  `train_batch` counts local steps and reconciles every `sync_period`
  (max_async_count parity: the drift bound). Works per-process (each
  process owns one island) or with several islands in one process
  (testing / simulation), via `sync_group=` a list of Parameters to
  average with.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.utils.stats import global_counters


def tree_isfinite(tree) -> bool:
    """True when every float leaf of the pytree is finite — the PR 1
    guarded-step check applied to a whole parameter tree (one fused
    device reduction, one host sync)."""
    ok = jnp.ones((), jnp.bool_)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return bool(ok)


def filter_finite_rows(keys, grads, counter: str = "parallel/poisoned_rows"):
    """Row-wise form of the :meth:`AsyncSGDIsland.reconcile` isfinite
    guard, for SPARSE gradient pushes (the embedding client / shard
    path): a non-finite gradient ROW — one poisoned sample's embedding
    slice — is dropped from the update (counter + warning) instead of
    contaminating the shared table, exactly as a poisoned island's tree
    is dropped from the reconcile average. Returns the surviving
    ``(keys, grads)`` pair (numpy); all-poisoned batches come back
    empty, which upstream applies as a no-op."""
    keys = np.asarray(keys)
    grads = np.asarray(grads)
    finite = np.isfinite(grads).reshape(grads.shape[0], -1).all(axis=1)
    if finite.all():
        return keys, grads
    n_bad = int((~finite).sum())
    global_counters.bump(counter, n_bad)
    warnings.warn(
        f"{n_bad} sparse gradient row(s) non-finite at push; dropped "
        "from the update (reconcile guard applied row-wise)",
        stacklevel=2)
    return keys[finite], grads[finite]


def average_pytree(tree, valid: Optional[bool] = None):
    """Average a pytree of arrays across all jax processes.

    Every process must call this with the same structure (a collective).
    Single-process: returns the tree unchanged.

    valid: this process's vote on whether its OWN tree may enter the
    average (the reconcile isfinite guard). Invalid islands are
    weighted out — every process still participates in the collective
    (it must: allgather is a barrier) but a poisoned island's
    NaN/Inf tree is multiplied by zero instead of contaminating every
    peer. If every island votes invalid, the trees pass through
    unchanged (nothing sane to average towards)."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    if valid is None:
        w = jnp.ones((), jnp.float32)
    else:
        w = jnp.asarray(1.0 if valid else 0.0, jnp.float32)
    weights = multihost_utils.process_allgather(w)     # [P]
    n_valid = jnp.sum(weights)
    if float(n_valid) == 0.0:
        return tree

    def avg(x):
        g = multihost_utils.process_allgather(x)       # [P, ...]
        wshape = (-1,) + (1,) * (g.ndim - 1)
        zero_naned = jnp.where(
            jnp.isfinite(g), g, jnp.zeros_like(g))
        return (jnp.sum(zero_naned * weights.reshape(wshape), axis=0)
                / n_valid).astype(x.dtype)

    return jax.tree_util.tree_map(avg, tree)


def average_local(trees: Sequence):
    """Average parameter dicts of several in-process islands (the
    simulation/test path; also useful for model soups)."""
    out = []
    keys = trees[0].keys()
    for t in trees:
        assert t.keys() == keys, "islands must share parameter names"
    avg = {k: jnp.mean(jnp.stack([t[k] for t in trees]), axis=0)
           for k in keys}
    # each island gets an INDEPENDENT buffer: the jitted train step
    # donates its parameter buffers, so sharing one array across islands
    # would let island A's step delete island B's weights
    return [{k: v.copy() for k, v in avg.items()} for _ in trees]


class AsyncSGDIsland:
    """Local-SGD wrapper: train independently, reconcile periodically.

    trainer:      a paddle_tpu SGD instance (this island's)
    sync_period:  local steps between reconciliations — the staleness
                  bound (ParameterServer2's max_async_count role)
    sync_group:   None = average across jax PROCESSES (each process one
                  island); or a list of Parameters objects of sibling
                  in-process islands (this trainer's included).
    generation_source: optional zero-arg callable returning the elastic
                  coordinator's membership generation (an int —
                  ``lambda: coord.generation``, or the value handed to
                  SGD.train's ``on_reshape`` hook via ``notify_reshape``).
                  When the generation changes between batches the island
                  reconciles IMMEDIATELY instead of waiting out its
                  sync_period: a fleet that just grew or shrank
                  re-synchronizes its islands at the reshape boundary,
                  so a joiner (or the survivors of a leave) start the
                  new membership from the common average rather than
                  ``sync_period`` stale local steps.
    """

    def __init__(self, trainer, sync_period: int = 8,
                 sync_group: Optional[Sequence] = None,
                 generation_source=None):
        assert sync_period >= 1
        self.trainer = trainer
        self.sync_period = sync_period
        self.sync_group = sync_group
        self.generation_source = generation_source
        self._local_steps = 0
        self._last_generation: Optional[int] = None
        self.reshape_reconciles = 0

    def notify_reshape(self, generation: int):
        """Membership changed (SGD.train's ``on_reshape`` hook, or any
        out-of-band signal): reconcile now. Idempotent per generation —
        repeated notifications for the same reshape reconcile once."""
        if generation == self._last_generation:
            return
        self._last_generation = generation
        self.reshape_reconciles += 1
        global_counters.bump("parallel/reshape_reconciles")
        self.reconcile()

    def _poll_generation(self):
        if self.generation_source is None:
            return
        gen = self.generation_source()
        if self._last_generation is None:
            self._last_generation = gen      # baseline, not a reshape
            return
        if gen != self._last_generation:
            self.notify_reshape(gen)

    def train_batch(self, batch, feeding=None):
        self._poll_generation()
        loss, metrics = self.trainer.train_batch(batch, feeding)
        self._local_steps += 1
        if self._local_steps % self.sync_period == 0:
            self.reconcile()
        return loss, metrics

    def reconcile(self):
        """Average parameters across the island group now.

        Guarded (the PR 1 isfinite discipline applied to reconcile): an
        island whose parameters went NaN/Inf — a poisoned batch that
        slipped through, an overflowed optimizer slot — is DROPPED from
        the average (logged + ``parallel/poisoned_islands`` counter in
        utils/stats) instead of contaminating every peer; the poisoned
        island itself is healed by adopting the clean islands' average.
        If every island is poisoned, reconcile is a no-op (nothing sane
        to average towards) and the caller's FaultPolicy rollback is the
        remaining recovery path."""
        if self.sync_group is None:
            own = self.trainer.parameters.raw
            ok = tree_isfinite(own)
            if not ok:
                global_counters.bump("parallel/poisoned_islands")
                warnings.warn(
                    "this island's parameters are non-finite at "
                    "reconcile; its tree is dropped from the average "
                    "and replaced by the healthy islands'",
                    stacklevel=2)
            self.trainer.parameters.replace(
                average_pytree(own, valid=ok))
        else:
            raws = [p.raw for p in self.sync_group]
            finite = [tree_isfinite(r) for r in raws]
            bad = [i for i, f in enumerate(finite) if not f]
            if bad:
                global_counters.bump("parallel/poisoned_islands",
                                     len(bad))
                warnings.warn(
                    f"island(s) {bad} have non-finite parameters at "
                    "reconcile; dropping their trees from the average "
                    f"({len(raws) - len(bad)} healthy island(s) "
                    "remain)", stacklevel=2)
            good = [r for r, f in zip(raws, finite) if f]
            if not good:
                warnings.warn(
                    "every island's parameters are non-finite; "
                    "skipping reconcile (rollback/fault policy is the "
                    "remaining recovery)", stacklevel=2)
                return
            averaged = average_local(good)
            # every island (poisoned ones included) adopts the healthy
            # average — the drop is from the INPUT, not the delivery
            clean = averaged[0]
            for p in self.sync_group:
                p.replace({k: v.copy() for k, v in clean.items()})
