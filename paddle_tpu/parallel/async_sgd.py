"""Asynchronous data parallelism, TPU-native form: local SGD islands.

Reference: the async-SGD path — `ParameterServer2::asyncSGD`
(paddle/pserver/ParameterServer2.cpp:457) and the Go pserver's
barrier-free `SendGrad` (go/pserver/service.go:221) — lets each trainer
push gradients and fetch parameters WITHOUT waiting for its peers, with
`max_async_count` bounding staleness. The payoff is straggler tolerance;
the price is stale gradients.

On TPU the intra-slice case is moot: the synchronous in-program
all-reduce over ICI is faster than any parameter-server hop, so "async
within a slice" would be a de-optimization. The case that survives is
ACROSS loosely-coupled workers (separate hosts/processes over DCN,
preemptible pools): there, the modern equivalent of async SGD is
**local SGD** — every island steps independently on its own shard
(parameters allowed to drift = bounded staleness), and islands
periodically reconcile by averaging parameters instead of streaming
per-step gradients through a server. Same tolerance property, no server,
and the reconciliation is one collective.

Two surfaces:

- `average_pytree(tree)` — cross-PROCESS parameter averaging (the
  reconciliation collective), built on multihost allgather; identity in
  single-process runs.
- `AsyncSGDIsland(trainer, sync_period)` — wraps an SGD trainer; its
  `train_batch` counts local steps and reconciles every `sync_period`
  (max_async_count parity: the drift bound). Works per-process (each
  process owns one island) or with several islands in one process
  (testing / simulation), via `sync_group=` a list of Parameters to
  average with.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def average_pytree(tree):
    """Average a pytree of arrays across all jax processes.

    Every process must call this with the same structure (a collective).
    Single-process: returns the tree unchanged."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    def avg(x):
        g = multihost_utils.process_allgather(x)   # [P, ...]
        return jnp.mean(g, axis=0).astype(x.dtype)

    return jax.tree_util.tree_map(avg, tree)


def average_local(trees: Sequence):
    """Average parameter dicts of several in-process islands (the
    simulation/test path; also useful for model soups)."""
    out = []
    keys = trees[0].keys()
    for t in trees:
        assert t.keys() == keys, "islands must share parameter names"
    avg = {k: jnp.mean(jnp.stack([t[k] for t in trees]), axis=0)
           for k in keys}
    # each island gets an INDEPENDENT buffer: the jitted train step
    # donates its parameter buffers, so sharing one array across islands
    # would let island A's step delete island B's weights
    return [{k: v.copy() for k, v in avg.items()} for _ in trees]


class AsyncSGDIsland:
    """Local-SGD wrapper: train independently, reconcile periodically.

    trainer:      a paddle_tpu SGD instance (this island's)
    sync_period:  local steps between reconciliations — the staleness
                  bound (ParameterServer2's max_async_count role)
    sync_group:   None = average across jax PROCESSES (each process one
                  island); or a list of Parameters objects of sibling
                  in-process islands (this trainer's included).
    """

    def __init__(self, trainer, sync_period: int = 8,
                 sync_group: Optional[Sequence] = None):
        assert sync_period >= 1
        self.trainer = trainer
        self.sync_period = sync_period
        self.sync_group = sync_group
        self._local_steps = 0

    def train_batch(self, batch, feeding=None):
        loss, metrics = self.trainer.train_batch(batch, feeding)
        self._local_steps += 1
        if self._local_steps % self.sync_period == 0:
            self.reconcile()
        return loss, metrics

    def reconcile(self):
        """Average parameters across the island group now."""
        if self.sync_group is None:
            self.trainer.parameters.replace(
                average_pytree(self.trainer.parameters.raw))
        else:
            raws = [p.raw for p in self.sync_group]
            averaged = average_local(raws)
            for p, a in zip(self.sync_group, averaged):
                p.replace(a)
