"""Sequence/context parallelism — ring attention over the mesh's `sp` axis.

The reference (2017) had no sequence parallelism; its long-sequence story
was ragged batching (Argument::sequenceStartPositions, SequenceToBatch).
This module is the modern successor SURVEY.md §2.4/§7 calls for: sequences
are sharded over chips on the time axis, and attention runs as a RING —
each chip holds its Q block, while K/V blocks rotate around the `sp` axis
via lax.ppermute; a running online-softmax (row max + normalizer) merges
per-block partial results so the full [T, T] score matrix never
materializes. Communication rides ICI neighbor-to-neighbor (the same
pattern as MultiGradientMachine's grad ring, MultiGradientMachine.h:61-83,
but over sequence blocks instead of gradient chunks).

All code is jit/shard_map-compatible and differentiable (the backward pass
is jax.grad through the scan + ppermute, which XLA reverses into the
mirror ring).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel._compat import shard_map

from paddle_tpu.parallel.mesh import SP_AXIS


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              mask: Optional[jnp.ndarray] = None,
              scale: Optional[float] = None) -> jnp.ndarray:
    """Plain scaled-dot-product attention, the single-chip reference.

    q: [b, Tq, h, d]; k, v: [b, Tk, h, d]; mask: [b, Tq, Tk] additive-bool
    (True = attend). Returns [b, Tq, h, d].
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask[:, None, :, :], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _ring_attention_local(q, k, v, q_valid, kv_valid, axis_name, causal,
                          q_offset, scale):
    """Per-shard body. q: [b, Tq, h, d] (local block); k/v: [b, Tk, h, d]
    (local block, will rotate). *_valid: [b, T*] bool masks for ragged
    sequences. q_offset is the global start position of the local Q block
    (for causal masking); K/V block positions follow from the rotation
    source index.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5

    # running accumulators for online softmax
    acc = jnp.zeros((b, tq, h, d), jnp.float32)
    row_max = jnp.full((b, h, tq), -1e30, jnp.float32)
    row_sum = jnp.zeros((b, h, tq), jnp.float32)

    q32 = q.astype(jnp.float32) * scale
    q_pos = q_offset + jnp.arange(tq)                       # [tq] global

    def body(carry, i):
        acc, row_max, row_sum, k_blk, v_blk, kv_valid_blk = carry
        # which shard's block are we holding? (blocks rotate backwards)
        src = (me + i) % n
        kv_pos = src * tk + jnp.arange(tk)                  # [tk] global
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32))
        valid = kv_valid_blk[:, None, None, :]              # [b,1,1,tk]
        if causal:
            cmask = (q_pos[:, None] >= kv_pos[None, :])     # [tq,tk]
            valid = jnp.logical_and(valid, cmask[None, None, :, :])
        logits = jnp.where(valid, logits, -1e30)

        blk_max = jnp.max(logits, axis=-1)                  # [b,h,tq]
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])            # [b,h,tq,tk]
        p = jnp.where(valid, p, 0.0)
        blk_sum = jnp.sum(p, axis=-1)
        new_sum = row_sum * correction + blk_sum
        pv = jnp.einsum("bhqk,bkhd->bqhd", p,
                        v_blk.astype(jnp.float32))
        new_acc = acc * correction.transpose(0, 2, 1)[..., None] + pv

        # rotate kv to the next chip (neighbor ring over ICI); the last
        # iteration's blocks are never read, so skip that hop
        def rotate(blks):
            perm = [(j, (j - 1) % n) for j in range(n)]
            return tuple(lax.ppermute(x, axis_name, perm) for x in blks)

        k_nxt, v_nxt, kv_valid_nxt = lax.cond(
            i < n - 1, rotate, lambda blks: blks,
            (k_blk, v_blk, kv_valid_blk))
        return (new_acc, new_max, new_sum, k_nxt, v_nxt, kv_valid_nxt), None

    init = (acc, row_max, row_sum, k, v, kv_valid)
    (acc, row_max, row_sum, _, _, _), _ = lax.scan(
        body, init, jnp.arange(n))
    norm = jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    out = acc / norm
    out = jnp.where(q_valid[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *,
                   lengths: Optional[jnp.ndarray] = None,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   axis_name: str = SP_AXIS) -> jnp.ndarray:
    """Context-parallel attention: time axis sharded over `axis_name`.

    q/k/v: [b, T, h, d] GLOBAL arrays (jit will keep them sharded over sp);
    lengths: [b] valid lengths for ragged batches. T must divide the sp
    axis size. Differentiable; call inside or outside jit.
    """
    n = mesh.shape[axis_name]
    b, t, h, d = q.shape
    assert t % n == 0, f"sp={n} must divide seq len {t}"
    tb = t // n
    if lengths is None:
        valid = jnp.ones((b, t), bool)
    else:
        valid = jnp.arange(t)[None, :] < lengths[:, None]

    def local(q_blk, k_blk, v_blk, q_val, kv_val):
        me = lax.axis_index(axis_name)
        q_offset = me * tb
        return _ring_attention_local(q_blk, k_blk, v_blk, q_val, kv_val,
                                     axis_name, causal, q_offset, scale)

    sp = P(None, axis_name, None, None)
    spv = P(None, axis_name)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(sp, sp, sp, spv, spv),
                   out_specs=sp, check=False)
    return fn(q, k, v, valid, valid)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, *,
                      lengths: Optional[jnp.ndarray] = None,
                      causal: bool = False,
                      axis_name: str = SP_AXIS) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): resharding
    [b, T/n, h, d] -> [b, T, h/n, d] via all_to_all so each chip computes
    FULL attention for a HEAD slice, then reshards back. One all-to-all
    each way over ICI instead of n ppermute hops — better when h >= n and
    the sequence fits per-chip HBM."""
    n = mesh.shape[axis_name]
    b, t, h, d = q.shape
    assert t % n == 0 and h % n == 0, (t, h, n)
    if lengths is None:
        valid = jnp.ones((b, t), bool)
    else:
        valid = jnp.arange(t)[None, :] < lengths[:, None]

    def local(q_blk, k_blk, v_blk, val):
        # [b, tb, h, d] -> all_to_all -> [b, t, h/n, d]
        def reshard(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)
        qg, kg, vg = reshard(q_blk), reshard(k_blk), reshard(v_blk)
        val_g = lax.all_gather(val, axis_name, axis=1, tiled=True)
        mask = val_g[:, None, :]                            # [b, 1, T]
        mask = jnp.broadcast_to(mask, (b, t, t))
        if causal:
            cm = jnp.tril(jnp.ones((t, t), bool))
            mask = jnp.logical_and(mask, cm[None])
        out = attention(qg, kg, vg, mask)
        # zero padded query rows (same contract as ring_attention)
        out = jnp.where(val_g[:, :, None, None], out, 0.0)
        # [b, t, h/n, d] -> back to [b, tb, h, d]
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    sp = P(None, axis_name, None, None)
    spv = P(None, axis_name)
    fn = shard_map(local, mesh=mesh, in_specs=(sp, sp, sp, spv),
                   out_specs=sp, check=False)
    return fn(q, k, v, valid)
