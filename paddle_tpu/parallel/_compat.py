"""shard_map compatibility: jax >= 0.8 exposes jax.shard_map with
`check_vma`; older versions have jax.experimental.shard_map with
`check_rep`. One shim so every call site works on both."""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _KW = "check_vma"
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_KW: check})
