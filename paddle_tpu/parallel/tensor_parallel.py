"""Tensor/model-parallel parameter sharding.

Replaces the reference's model-parallel machinery — ParallelNeuralNetwork's
per-layer device pinning (ParallelNeuralNetwork.h:34-63, `--parallel_nn`)
and the pserver's block-sharded parameter storage (ParameterServer2.h:78-95:
parameters split into 64KB blocks scattered over servers) — with GSPMD
sharding annotations: each parameter gets a PartitionSpec over the mesh's
`mp` axis, XLA partitions the matmuls and inserts the collectives over ICI.

Default rules (the scaling-book recipe for this layer vocabulary):
  - embedding tables  (vocab, emb)   -> row-sharded  P("mp", None):
    the sparse-remote-update capability (embedding rows living on pservers,
    MultiGradientMachine.h:99-166) becomes rows-living-on-chips.
  - fc/projection weights (in, out)  -> column-sharded P(None, "mp")
    (output features split; XLA all-gathers activations only when needed).
  - conv kernels (kh, kw, ic, oc)    -> P(None, None, None, "mp") when oc
    divides; spatial conv stays local, channel reduce rides ICI.
  - biases / gains / 1-D state      -> replicated.

Use `default_rules()` for the defaults or pass custom `(regex, spec)`
pairs to `spec_for`/`param_shardings`, which skip any param whose dims
don't divide the axis (falling back to replication).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import EP_AXIS, MP_AXIS


Rule = Tuple[str, P]


def default_rules() -> List[Rule]:
    return [
        (r".*\.moe_(up|down)$", P(EP_AXIS, None, None)),    # expert tables
        (r".*emb.*\.w0$|.*emb.*_w$", P(MP_AXIS, None)),     # embedding rows
        (r".*\.w\d+$|.*_w$", P(None, MP_AXIS)),             # fc columns
        (r".*wbias$|.*_b$|.*moving_.*", P()),               # 1-D: replicate
    ]


def _spec_fits(shape: Sequence[int], spec: P, mesh: Mesh) -> bool:
    """Every sharded dim must exist and divide the mesh axis size."""
    if len(spec) > len(shape):
        return False
    for dim, axis in zip(shape, spec):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        if any(a not in mesh.shape for a in axes):
            return False        # rule names an axis this mesh doesn't have
        n = int(np.prod([mesh.shape[a] for a in axes]))
        if dim % n != 0:
            return False
    return True


def spec_for(name: str, shape: Sequence[int], mesh: Mesh,
             rules: Optional[Sequence[Rule]] = None) -> P:
    """PartitionSpec for one parameter (first matching + fitting rule;
    rules whose axes the mesh lacks — e.g. mp rules on a dp-only mesh,
    the ep rule on a mesh without experts — fall back to replication)."""
    ndim = len(shape)
    for pat, spec in (rules or default_rules()):
        if re.match(pat, name):
            # conv kernels: shard the last (out-channel) dim instead of cols
            if ndim == 4 and spec == P(None, MP_AXIS):
                spec = P(None, None, None, MP_AXIS)
            if len(spec) <= ndim and _spec_fits(shape, spec, mesh):
                return spec
            return P()
    return P()


def param_shardings(param_specs: Dict[str, "ParamSpec"], mesh: Mesh,
                    rules: Optional[Sequence[Rule]] = None
                    ) -> Dict[str, NamedSharding]:
    """Name -> NamedSharding for a topology's parameter table."""
    return {name: NamedSharding(mesh, spec_for(name, tuple(ps.shape), mesh,
                                               rules))
            for name, ps in param_specs.items()}


def shard_params(params: Dict[str, jax.Array], mesh: Mesh,
                 shardings: Dict[str, NamedSharding]) -> Dict[str, jax.Array]:
    """Place a host/replicated param dict onto the mesh per the shardings."""
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def opt_state_shardings(opt_state, param_shardings: Dict[str, NamedSharding],
                        mesh: Mesh):
    """Optimizer slots mirror their parameter's sharding (momentum/adam
    moments have the param's shape); scalars replicate. This is the
    pserver-parity move: optimizer state lives WITH the shard
    (ParameterServer2 runs op_SGD on its local block)."""
    repl = NamedSharding(mesh, P())

    def assign(path, leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return repl
        for p in path:
            key = getattr(p, "key", None)
            if key in param_shardings:
                sh = param_shardings[key]
                if len(sh.spec) <= leaf.ndim:
                    return sh
                # lower-rank slot (e.g. the per-row "_t" clock [vocab] of a
                # row-sharded [vocab, emb] table): keep the leading axes
                return NamedSharding(mesh, P(*sh.spec[: leaf.ndim]))
        return repl

    return jax.tree_util.tree_map_with_path(assign, opt_state)
