"""Data-parallel training step sharding.

Reference: MultiGradientMachine (single host, ring allreduce over GPU
threads, MultiGradientMachine.h:44-98) and RemoteParameterUpdater +
ParameterServer2 sync barriers (multi-host). Here both collapse into ONE
jit: batch sharded over the `dp` mesh axis, parameters replicated, and XLA
emits the gradient all-reduce over ICI automatically because the grads of
replicated params depend on sharded data. `trainer_count` maps to the dp
axis size.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.parallel.mesh import DP_AXIS


def _feed_shardings(feed, mesh: Mesh):
    """Batch-shard every feed leaf over dp (leading axis); on meshes with
    no dp axis (pure tensor-parallel) the feed stays replicated."""
    spec = P(DP_AXIS) if DP_AXIS in mesh.shape else P()

    def leaf(x):
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(leaf, feed)


def shard_train_step(step_fn: Callable, mesh: Mesh,
                     param_shardings=None, opt_shardings=None,
                     n_extra: int = 0) -> Callable:
    """Wrap a train step (params, opt_state, state, feed, rng, n_real) so the
    feed is dp-sharded over the mesh. Params/opt-state are replicated by
    default; pass `param_shardings` (name -> NamedSharding, from
    parallel.tensor_parallel) and matching `opt_shardings` for dp x mp runs
    — XLA then partitions the matmuls over `mp` and all-reduces grads over
    `dp`, replacing both MultiGradientMachine's ring and the pserver.

    n_extra: replicated scalar carries appended to both the argument and
    result lists (the guarded step's bad-step streak counter)."""
    repl = NamedSharding(mesh, P())

    def sharded(params, opt_state, state, feed, rng, n_real, *extra):
        feed = jax.lax.with_sharding_constraint(
            feed, _feed_shardings(feed, mesh))
        return step_fn(params, opt_state, state, feed, rng, n_real, *extra)

    # out_shardings must pin the params/opt outputs to the SAME shardings as
    # the inputs: otherwise XLA's propagated output shardings (e.g. a bias
    # grad picking up mp from its matmul) poison the next call's args.
    # The 6th output (evaluator input values) is gathered to replicated so
    # host-side evaluators see the full batch.
    return jax.jit(
        sharded,
        in_shardings=(param_shardings or repl, opt_shardings or repl,
                      repl, None, repl, repl) + (repl,) * n_extra,
        out_shardings=(param_shardings or repl, opt_shardings or repl,
                       repl, repl, repl, repl) + (repl,) * n_extra,
        donate_argnums=(0, 1, 2),
    )


def shard_feed(feed, mesh: Mesh):
    """Place a host feed onto the mesh dp-sharded (device_put)."""
    return jax.device_put(feed, _feed_shardings(feed, mesh))


def microbatch_shardings(feed_m, mesh: Mesh):
    """Shardings for a ``(k, mb, ...)`` microbatched feed (the
    gradient-accumulation step, trainer/memory.py): the accumulation
    axis is a TIME axis — replicated, every device scans all k ticks —
    while the per-microbatch ROW axis keeps the dp split, so each
    device accumulates over its own rows and the gradient all-reduce
    still happens once on the summed grads, not per microbatch."""
    spec = P(None, DP_AXIS) if DP_AXIS in mesh.shape else P()

    def leaf(x):
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(leaf, feed_m)


def shard_microbatched_feed(feed_m, mesh: Mesh):
    """Constrain a reshaped ``(k, mb, ...)`` feed inside the jitted
    accumulation step. The reshape alone would let sharding propagation
    split the leading k axis over dp — handing each device a fraction
    of the accumulation STEPS instead of a fraction of the rows, which
    serializes the scan across devices."""
    return jax.lax.with_sharding_constraint(
        feed_m, microbatch_shardings(feed_m, mesh))
