"""Data-parallel training step sharding.

Reference: MultiGradientMachine (single host, ring allreduce over GPU
threads, MultiGradientMachine.h:44-98) and RemoteParameterUpdater +
ParameterServer2 sync barriers (multi-host). Here both collapse into ONE
jit: batch sharded over the `dp` mesh axis, parameters replicated, and XLA
emits the gradient all-reduce over ICI automatically because the grads of
replicated params depend on sharded data. `trainer_count` maps to the dp
axis size.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.parallel.mesh import DP_AXIS


def _feed_shardings(feed, mesh: Mesh):
    """Batch-shard every feed leaf over dp (leading axis)."""
    def leaf(x):
        return NamedSharding(mesh, P(DP_AXIS))
    return jax.tree_util.tree_map(leaf, feed)


def shard_train_step(step_fn: Callable, mesh: Mesh) -> Callable:
    """Wrap a train step (params, opt_state, state, feed, rng, n_real) so the
    feed is dp-sharded and params/opt state replicated."""
    repl = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P(DP_AXIS))

    def sharded(params, opt_state, state, feed, rng, n_real):
        feed = jax.lax.with_sharding_constraint(
            feed, _feed_shardings(feed, mesh))
        return step_fn(params, opt_state, state, feed, rng, n_real)

    return jax.jit(
        sharded,
        in_shardings=(repl, repl, repl, None, repl, repl),
        donate_argnums=(0, 1, 2),
    )


def shard_feed(feed, mesh: Mesh):
    """Place a host feed onto the mesh dp-sharded (device_put)."""
    return jax.device_put(feed, _feed_shardings(feed, mesh))
