"""Device meshes — the distribution substrate.

Replaces the reference's entire communication plane: MultiGradientMachine's
in-process ring allreduce (MultiGradientMachine.h:61-83), the
pserver sharded-parameter RPC stack (ParameterServer2/ParameterClient2,
LightNetwork TCP/RDMA), and the Go cloud runtime's gradient plumbing — all
become sharding annotations over a `jax.sharding.Mesh`; XLA inserts the
collectives (all-reduce / all-gather / reduce-scatter) and routes them over
ICI within a slice and DCN across slices.

Axis conventions (the scaling-book recipe):
  dp — data parallel (batch dim)          <- trainer_count / num_gradient_servers
  mp — model/tensor parallel (features)   <- parallel_nn device placement
  sp — sequence/context parallel (time)   <- (new; no 2017 equivalent)
  pp — pipeline stages                    <- ParallelNeuralNetwork layer pinning
  ep — expert parallel (MoE expert dim)   <- (new; no 2017 equivalent)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
MP_AXIS = "mp"
SP_AXIS = "sp"
PP_AXIS = "pp"
EP_AXIS = "ep"


def create_mesh(shape: Sequence[Tuple[str, int]],
                devices=None) -> Mesh:
    """create_mesh([("dp", 4), ("mp", 2)]) over local/global devices."""
    if devices is None:
        devices = jax.devices()
    names = [n for n, _ in shape]
    dims = [d for _, d in shape]
    total = int(np.prod(dims))
    assert total <= len(devices), \
        f"mesh needs {total} devices, have {len(devices)}"
    arr = np.asarray(devices[:total]).reshape(dims)
    return Mesh(arr, axis_names=tuple(names))


def data_parallel_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = n or len(devices)
    return create_mesh([(DP_AXIS, n)], devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (batch) dim over dp."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
