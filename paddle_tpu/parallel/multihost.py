"""Multi-host runtime glue — the cluster entry point.

Reference: the v1 cluster launcher wires trainer_id / num_gradient_servers
/ pserver endpoints through flags (Flags.cpp:55-60, TrainerMain.cpp:32-58,
RemoteParameterUpdater's pserver hand-off); the Go runtime
(go/cmd/pserver, master) discovers peers via etcd.

TPU-native design: `jax.distributed.initialize` forms the process group
(coordinator address = the etcd/pserver-endpoint equivalent); after it
returns, jax.devices() spans EVERY host and the same single-jit
dp/mp/pp/sp program from parallel/ runs unchanged — XLA routes
collectives over ICI within a slice and DCN across hosts. The only
per-process code is data: each process feeds its own shard
(process_reader) and jax.make_array_from_process_local_data assembles the
global batch.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np

from paddle_tpu import config as config_mod


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None,
                     connect_attempts: int = 3,
                     connect_backoff_s: float = 2.0,
                     **kw) -> Tuple[int, int]:
    """Join (or form) the multi-host process group.

    Mirrors `paddle train --trainer_id=i --num_gradient_servers=n
    --pservers=host:port,...` (Flags.cpp:55-60): coordinator_address plays
    the pserver-endpoint/etcd role. No-args works under TPU cluster
    schedulers that set the environment (GKE/Borg metadata), matching the
    reference's cloud auto-discovery. Returns (process_index,
    process_count) and records them in the global config.

    An explicitly-requested cluster whose coordinator is not up YET (a
    scheduler starting N processes in arbitrary order) is retried
    ``connect_attempts`` times with ``connect_backoff_s * 2^i`` waits
    before the connection error propagates.
    """
    # IMPORTANT: nothing may touch the XLA backend (jax.devices/
    # process_count) before jax.distributed.initialize, or it raises.
    already = False
    try:
        already = jax.distributed.is_initialized()
    except AttributeError:   # older jax: probe the client handle
        already = getattr(getattr(jax._src.distributed, "global_state", None),
                          "client", None) is not None
    if not already:
        for attempt in range(max(connect_attempts, 1)):
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    local_device_ids=local_device_ids, **kw)
                break
            except ValueError:
                # ValueError is jax's arg-validation signal ("coordinator_
                # address should be defined") — i.e. auto-detect found NO
                # cluster environment. Only that case may fall back to a
                # standalone single-process run, and only when the caller
                # passed no explicit cluster args.
                if coordinator_address or num_processes:
                    raise
                break
            except RuntimeError as e:
                # "must be called before any JAX calls" = the backend is
                # already warm in a standalone process; same no-cluster
                # fallback, but an explicit cluster request must still fail
                if not (coordinator_address or num_processes) and \
                        "before" in str(e):
                    break
                # a REAL cluster error: a scheduler environment was
                # detected but the coordinator is unreachable. Startup
                # races (coordinator pod not up yet) get bounded
                # exponential-backoff retries; a coordinator that never
                # appears re-raises rather than silently training this
                # process on 1/N of the data.
                if attempt + 1 >= max(connect_attempts, 1):
                    raise
                import time
                import warnings
                wait = connect_backoff_s * (2.0 ** attempt)
                warnings.warn(
                    f"jax.distributed.initialize failed ({e}); retry "
                    f"{attempt + 1}/{connect_attempts} in {wait:.1f}s")
                time.sleep(wait)
    g = config_mod.global_config()
    g.process_index = jax.process_index()
    g.process_count = jax.process_count()
    return g.process_index, g.process_count


def process_reader(reader: Callable, process_index: Optional[int] = None,
                   process_count: Optional[int] = None) -> Callable:
    """Deal a global reader's samples round-robin to this process.

    The per-process half of multi-host data parallelism: every process
    runs the same reader pipeline but keeps samples where
    `i % process_count == process_index` — the deterministic equivalent of
    the reference's per-trainer file-list split
    (cluster_train/conf.py trainer splits + master task dispatch).
    """
    g = config_mod.global_config()
    pi = g.process_index if process_index is None else process_index
    pc = g.process_count if process_count is None else process_count

    def sharded():
        for i, sample in enumerate(reader()):
            if i % pc == pi:
                yield sample

    return sharded


def global_batch(local_batch, mesh, spec) -> jax.Array:
    """Assemble a globally-sharded array from each process's local shard.

    local_batch: this process's rows (numpy). mesh/spec: the global
    dp-sharding the train step expects. Single-process: a plain
    device_put. Multi-process: jax.make_array_from_process_local_data
    builds the global jax.Array without gathering — the
    ParameterServer-free replacement for distributing the global batch.
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec) if not hasattr(spec, "mesh") \
        else spec
    local_batch = np.asarray(local_batch)
    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    return jax.make_array_from_process_local_data(sharding, local_batch)


def is_coordinator() -> bool:
    """True on the process that should write checkpoints / logs (the
    reference's trainer_id==0 convention)."""
    return jax.process_index() == 0
