from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.mesh import (create_mesh, data_parallel_mesh,
                                      DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)

__all__ = ["mesh_mod", "create_mesh", "data_parallel_mesh", "DP_AXIS",
           "MP_AXIS", "PP_AXIS", "SP_AXIS"]
