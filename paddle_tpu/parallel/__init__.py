from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.mesh import (create_mesh, data_parallel_mesh,
                                      DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)
from paddle_tpu.parallel import tensor_parallel
from paddle_tpu.parallel import sequence_parallel
from paddle_tpu.parallel import pipeline as pipeline_mod
from paddle_tpu.parallel.sequence_parallel import (attention, ring_attention,
                                                   ulysses_attention)

__all__ = ["mesh_mod", "create_mesh", "data_parallel_mesh", "DP_AXIS",
           "MP_AXIS", "PP_AXIS", "SP_AXIS", "tensor_parallel",
           "sequence_parallel", "pipeline_mod", "attention",
           "ring_attention", "ulysses_attention"]
from paddle_tpu.parallel.multihost import (init_distributed,  # noqa: F401
                                           process_reader, global_batch,
                                           is_coordinator)
from paddle_tpu.parallel.async_sgd import (AsyncSGDIsland,  # noqa: F401
                                           average_pytree, average_local)
