"""Pipeline parallelism — GPipe-style microbatch pipelining over the `pp`
mesh axis.

Reference parity: ParallelNeuralNetwork (ParallelNeuralNetwork.h:34-63,
`--parallel_nn`) pinned layers to devices (`deviceId` per layer) and ran
per-device compute threads with async queues between them. TPU-native, the
same capability is a shard_map over `pp`: each chip holds ONE stage's
parameters, activations hop to the next stage via lax.ppermute over ICI,
and a lax.scan over (microbatches + stages - 1) ticks keeps every chip
busy once the pipeline fills (the bubble is the standard (n-1)/(m+n-1)).

Differentiable end-to-end: jax.grad reverses the scan and the ppermutes
into the mirrored backward ring — no hand-written backward schedule.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel._compat import shard_map

from paddle_tpu.parallel.mesh import PP_AXIS


def pipeline(stage_fn: Callable, stage_params, x: jnp.ndarray, mesh: Mesh,
             num_microbatches: Optional[int] = None,
             axis_name: str = PP_AXIS, remat: bool = False) -> jnp.ndarray:
    """Run `stage_fn` as an n-stage pipeline.

    stage_fn(params_i, x_mb) -> y_mb, shape-preserving ([mb, ...] in/out).
    stage_params: pytree whose leaves have a leading `n_stages` axis
      (stage i's slice lives on chip i — sharded over `pp`).
    x: [batch, ...] global input; split into `num_microbatches` equal
      microbatches (default: n_stages, the minimum that fills the ring).
    remat: wrap each stage in jax.checkpoint so the backward pass holds
      only stage-BOUNDARY activations per tick and recomputes the stage
      interior — the FLOPs-for-memory trade (identical numerics; the
      standard companion of microbatch pipelining, since scan otherwise
      saves every tick's interior residuals for the reversed pass).

    Returns [batch, ...] outputs (replicated over pp).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == n, \
            f"stage_params leading axis {leaf.shape[0]} != pp={n}"
    b = x.shape[0]
    m = num_microbatches or n
    assert b % m == 0, f"microbatches {m} must divide batch {b}"
    mb = b // m
    xm = x.reshape((m, mb) + x.shape[1:])

    def local(params, xm_local):
        # params: stage slice [1, ...] -> squeeze; xm_local: full [m, mb,...]
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        me = lax.axis_index(axis_name)
        ticks = m + n - 1

        state0 = jnp.where(me == 0, xm_local[0], jnp.zeros_like(xm_local[0]))
        outbuf0 = jnp.zeros_like(xm_local)

        def tick(carry, t):
            state, outbuf = carry
            y = stage_fn(params, state)
            # collect on the last stage: tick t finishes microbatch t-(n-1)
            oi = jnp.clip(t - (n - 1), 0, m - 1)
            take = jnp.logical_and(me == n - 1, t >= n - 1)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(take, y, outbuf[oi]), oi, 0)
            # hop activations forward one stage
            y_prev = lax.ppermute(y, axis_name,
                                  [(i, i + 1) for i in range(n - 1)])
            xi = jnp.clip(t + 1, 0, m - 1)
            nxt = jnp.where(me == 0, xm_local[xi], y_prev)
            return (nxt, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (state0, outbuf0),
                                  jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them
        outbuf = jnp.where(me == n - 1, outbuf, jnp.zeros_like(outbuf))
        return lax.psum(outbuf, axis_name)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(pspec, P()), out_specs=P(),
                   check=False)
    out = fn(stage_params, xm)
    return out.reshape((b,) + out.shape[2:])


def topology_stages(topology, stage_names):
    """Build the pipeline pieces for a Topology-defined model.

    stage_names: list (one entry per pp rank) of lists of layer names —
    the explicit stage map, the TPU-native form of ParallelNeuralNetwork's
    per-layer `deviceId` pinning (ParallelNeuralNetwork.h:34, config
    `device=` attribute). Constraints (asserted): stages must be
    structurally identical (same layer types + param shapes — GPipe over
    a repeated block), each stage a linear chain whose first layer feeds
    from the previous stage's last (stage 0 from a data layer), and
    stateless (no batch-norm stats inside the body).

    Returns (stage_fn, stack_params, body_names, x_src, body_end):
      stage_fn(slot_params, x) — replays stage 0's layers with
        substituted params (all stages share its structure);
      stack_params(params) — {stage0 param name: [n_stages, ...] stack};
      body_names — every pipelined layer (to skip in the tail forward);
      x_src — the data layer feeding the pipeline;
      body_end — the final stage's last layer name (inject its value).
    """
    from paddle_tpu.core.registry import ApplyContext, get_layer_impl

    by_name = topology.by_name
    n = len(stage_names)
    sigs = []
    for si, st in enumerate(stage_names):
        sig = []
        for li, nm in enumerate(st):
            l = by_name[nm]
            assert not l.states, \
                f"stateful layer {nm!r} unsupported inside a pipeline stage"
            assert l.type != "dropout", \
                f"dropout ({nm!r}) unsupported inside a pipeline stage — " \
                "the stage context has no per-step rng (put dropout in " \
                "the tail, or between body and head)"
            assert len(l.parents) == 1, \
                f"pipeline stages must be linear chains; {nm!r} has " \
                f"{len(l.parents)} inputs"
            expect = st[li - 1] if li > 0 else (
                stage_names[si - 1][-1] if si > 0 else None)
            if expect is not None:
                assert l.parents[0].name == expect, \
                    f"{nm!r} must consume {expect!r}, got " \
                    f"{l.parents[0].name!r}"
            sig.append((l.type,
                        tuple(tuple(ps.shape) for ps in l.params)))
        sigs.append(tuple(sig))
    assert all(s == sigs[0] for s in sigs), \
        "pipeline stages must be structurally identical"
    first = by_name[stage_names[0][0]]
    assert first.parents[0].type == "data", \
        "the pipeline body must start right after a data layer"
    x_src = first.parents[0].name

    name_matrix = [[ps.name for nm in st for ps in by_name[nm].params]
                   for st in stage_names]
    slot_names = name_matrix[0]
    stage0 = [by_name[nm] for nm in stage_names[0]]

    def stage_fn(slot_params, x):
        ctx = ApplyContext("train", None, {})
        prev = x
        for l in stage0:
            impl = get_layer_impl(l.type)
            lp = {ps.name: slot_params[ps.name] for ps in l.params}
            prev = impl["apply"](ctx, l.name, l.config, lp, [prev])
        return prev

    def stack_params(params):
        return {slot_names[j]: jnp.stack(
            [params[name_matrix[i][j]] for i in range(n)])
            for j in range(len(slot_names))}

    def unstack(stacked):
        """{global param name: per-stage slice} from a stacked pytree —
        the inverse of stack_params, used to merge per-stage gradients
        back into the flat param-name space (1F1B path)."""
        return {name_matrix[i][j]: stacked[slot_names[j]][i]
                for i in range(n) for j in range(len(slot_names))}

    stack_params.unstack = unstack
    stack_params.param_names = {nm for row in name_matrix for nm in row}
    body_names = [nm for st in stage_names for nm in st]
    return stage_fn, stack_params, body_names, x_src, stage_names[-1][-1]


def pipeline_1f1b(stage_fn: Callable, stage_params, x: jnp.ndarray,
                  tail_vjp: Callable, mesh: Mesh,
                  num_microbatches: Optional[int] = None,
                  axis_name: str = PP_AXIS, tail_args=()):
    """One-forward-one-backward pipeline schedule (PipeDream-flush /
    Megatron 1F1B), hand-scheduled because the backward interleaving
    cannot be expressed through jax.grad of a forward scan.

    stage_fn(params_i, x_mb) -> y_mb, shape-preserving.
    stage_params: pytree with leading [n_stages] axis, sharded over pp.
    tail_vjp(y_mb, j, *tail_args) -> (loss_j, dy_mb, dtail_pytree):
      per-microbatch loss head — called at the LAST stage the moment
      microbatch j's forward completes, so its cotangent enters the
      backward ring in the same tick (the defining property of 1F1B).
    tail_args: pytrees the tail differentiates (params, feed slices) —
      threaded through the shard_map as replicated operands rather than
      captured in the closure, because cotangents of closure-captured
      committed arrays carry their Auto-mesh shardings into the Manual
      context and fail sharding-in-types checks.

    Returns (loss_sum, y [batch, ...], stage_grads stacked like
    stage_params, dtail_sum).

    Schedule: microbatch j runs forward at stage s on tick j+s and
    backward on tick j + 2(n-1) - s; one scan over m + 2(n-1) ticks
    carries a RING BUFFER of 2n-1 saved stage INPUTS (backward
    recomputes the stage from its input, vjp'd immediately — residuals
    never outlive a tick). Peak activation state is therefore O(n
    stages), independent of the microbatch count m, where the
    jax.grad-reversed GPipe scan must carry O(m + n) tick states: the
    memory-for-schedule trade that lets m grow (and the bubble
    (n-1)/(m+n-1) shrink) without OOM. Under SPMD every rank executes
    every tick's masked F and B slots, so at small m the extra n-1
    drain ticks cost wall-clock vs GPipe; the ratio (m+2n-2)/(m+n-1)
    approaches 1 in exactly the large-m regime 1F1B exists for.
    Reference analogue: ParallelNeuralNetwork's per-device compute
    threads with async queues (ParallelNeuralNetwork.h:34), modernized.
    """
    n = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == n, \
            f"stage_params leading axis {leaf.shape[0]} != pp={n}"
    b = x.shape[0]
    m = num_microbatches or n
    assert b % m == 0, f"microbatches {m} must divide batch {b}"
    mb = b // m
    xm = x.reshape((m, mb) + x.shape[1:])
    ring = 2 * n - 1

    def local(params, xm_local, targs):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        me = lax.axis_index(axis_name)
        zero_mb = jnp.zeros_like(xm_local[0])

        # probe shapes for the accumulators (abstract eval only)
        y_shape = jax.eval_shape(stage_fn, params, zero_mb)
        zero_y = jnp.zeros(y_shape.shape, y_shape.dtype)
        _, dy_probe, dtail_probe = jax.eval_shape(
            lambda y, ta: tail_vjp(y, jnp.int32(0), *ta), zero_y, targs)
        g_zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        dtail_zero = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), dtail_probe)

        carry0 = (zero_mb,                       # x_state: incoming act
                  jnp.zeros(dy_probe.shape, dy_probe.dtype),  # dy_state
                  jnp.zeros((ring,) + zero_mb.shape, zero_mb.dtype),
                  jnp.zeros((m,) + y_shape.shape, y_shape.dtype),
                  g_zero, dtail_zero, jnp.float32(0.0))

        def tick(carry, t):
            x_state, dy_state, inbuf, youtbuf, g_acc, dtail_acc, \
                loss_acc = carry
            # ---- forward slot: mb fj = t - me
            fj = t - me
            f_active = jnp.logical_and(fj >= 0, fj < m)
            fjc = jnp.clip(fj, 0, m - 1)
            x_in = jnp.where(me == 0, xm_local[fjc], x_state)
            y = stage_fn(params, x_in)
            slot_f = fjc % ring
            inbuf = lax.dynamic_update_index_in_dim(
                inbuf, jnp.where(f_active, x_in, inbuf[slot_f]), slot_f, 0)
            last = me == n - 1
            take_y = jnp.logical_and(last, f_active)
            youtbuf = lax.dynamic_update_index_in_dim(
                youtbuf, jnp.where(take_y, y, youtbuf[fjc]), fjc, 0)
            # ---- tail head (meaningful on the last stage only; SPMD
            # executes it everywhere, masked)
            loss_j, dy_tail, dtail_j = tail_vjp(y, fjc, *targs)
            loss_acc = loss_acc + jnp.where(take_y, loss_j, 0.0)
            dtail_acc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(take_y, d, jnp.zeros_like(d)),
                dtail_acc, dtail_j)
            # ---- backward slot: mb bj = t - 2(n-1) + me
            bj = t - 2 * (n - 1) + me
            b_active = jnp.logical_and(bj >= 0, bj < m)
            bjc = jnp.clip(bj, 0, m - 1)
            dy_in = jnp.where(last, dy_tail, dy_state)
            x_saved = inbuf[bjc % ring]
            _, svjp = jax.vjp(stage_fn, params, x_saved)
            dp_j, dx_j = svjp(dy_in)
            g_acc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(b_active, d, jnp.zeros_like(d)),
                g_acc, dp_j)
            # ---- hop: activations up, cotangents down
            y_prev = lax.ppermute(y, axis_name,
                                  [(i, i + 1) for i in range(n - 1)])
            dx_next = lax.ppermute(dx_j, axis_name,
                                   [(i, i - 1) for i in range(1, n)])
            return (y_prev, dx_next, inbuf, youtbuf, g_acc, dtail_acc,
                    loss_acc), None

        (x_s, dy_s, inbuf, youtbuf, g_acc, dtail_acc, loss_acc), _ = \
            lax.scan(tick, carry0, jnp.arange(m + 2 * (n - 1)))
        youtbuf = jnp.where(me == n - 1, youtbuf,
                            jnp.zeros_like(youtbuf))
        youtbuf = lax.psum(youtbuf, axis_name)
        loss_sum = lax.psum(jnp.where(me == n - 1, loss_acc, 0.0),
                            axis_name)
        dtail = jax.tree_util.tree_map(
            lambda d: lax.psum(jnp.where(me == n - 1, d,
                                         jnp.zeros_like(d)), axis_name),
            dtail_acc)
        g_out = jax.tree_util.tree_map(lambda g: g[None], g_acc)
        return loss_sum, youtbuf, g_out, dtail

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    gspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(pspec, P(), P()),
                   out_specs=(P(), P(), gspec, P()),
                   check=False)
    loss_sum, ym, g_stacked, dtail = fn(stage_params, xm, tuple(tail_args))
    return (loss_sum, ym.reshape((b,) + ym.shape[2:]), g_stacked, dtail)


def pipeline_loss(stage_fn: Callable, loss_fn: Callable):
    """Compose pipeline + loss into one differentiable objective:
    loss_fn(y, *args) applied to the pipeline output (e.g. softmax CE on
    the last stage's activations)."""
    def objective(stage_params, x, mesh, *loss_args, **kw):
        y = pipeline(stage_fn, stage_params, x, mesh, **kw)
        return loss_fn(y, *loss_args)
    return objective
