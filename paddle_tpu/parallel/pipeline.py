"""Pipeline parallelism — GPipe-style microbatch pipelining over the `pp`
mesh axis.

Reference parity: ParallelNeuralNetwork (ParallelNeuralNetwork.h:34-63,
`--parallel_nn`) pinned layers to devices (`deviceId` per layer) and ran
per-device compute threads with async queues between them. TPU-native, the
same capability is a shard_map over `pp`: each chip holds ONE stage's
parameters, activations hop to the next stage via lax.ppermute over ICI,
and a lax.scan over (microbatches + stages - 1) ticks keeps every chip
busy once the pipeline fills (the bubble is the standard (n-1)/(m+n-1)).

Differentiable end-to-end: jax.grad reverses the scan and the ppermutes
into the mirrored backward ring — no hand-written backward schedule.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel._compat import shard_map

from paddle_tpu.parallel.mesh import PP_AXIS


def pipeline(stage_fn: Callable, stage_params, x: jnp.ndarray, mesh: Mesh,
             num_microbatches: Optional[int] = None,
             axis_name: str = PP_AXIS, remat: bool = False) -> jnp.ndarray:
    """Run `stage_fn` as an n-stage pipeline.

    stage_fn(params_i, x_mb) -> y_mb, shape-preserving ([mb, ...] in/out).
    stage_params: pytree whose leaves have a leading `n_stages` axis
      (stage i's slice lives on chip i — sharded over `pp`).
    x: [batch, ...] global input; split into `num_microbatches` equal
      microbatches (default: n_stages, the minimum that fills the ring).
    remat: wrap each stage in jax.checkpoint so the backward pass holds
      only stage-BOUNDARY activations per tick and recomputes the stage
      interior — the FLOPs-for-memory trade (identical numerics; the
      standard companion of microbatch pipelining, since scan otherwise
      saves every tick's interior residuals for the reversed pass).

    Returns [batch, ...] outputs (replicated over pp).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == n, \
            f"stage_params leading axis {leaf.shape[0]} != pp={n}"
    b = x.shape[0]
    m = num_microbatches or n
    assert b % m == 0, f"microbatches {m} must divide batch {b}"
    mb = b // m
    xm = x.reshape((m, mb) + x.shape[1:])

    def local(params, xm_local):
        # params: stage slice [1, ...] -> squeeze; xm_local: full [m, mb,...]
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        me = lax.axis_index(axis_name)
        ticks = m + n - 1

        state0 = jnp.where(me == 0, xm_local[0], jnp.zeros_like(xm_local[0]))
        outbuf0 = jnp.zeros_like(xm_local)

        def tick(carry, t):
            state, outbuf = carry
            y = stage_fn(params, state)
            # collect on the last stage: tick t finishes microbatch t-(n-1)
            oi = jnp.clip(t - (n - 1), 0, m - 1)
            take = jnp.logical_and(me == n - 1, t >= n - 1)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(take, y, outbuf[oi]), oi, 0)
            # hop activations forward one stage
            y_prev = lax.ppermute(y, axis_name,
                                  [(i, i + 1) for i in range(n - 1)])
            xi = jnp.clip(t + 1, 0, m - 1)
            nxt = jnp.where(me == 0, xm_local[xi], y_prev)
            return (nxt, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (state0, outbuf0),
                                  jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them
        outbuf = jnp.where(me == n - 1, outbuf, jnp.zeros_like(outbuf))
        return lax.psum(outbuf, axis_name)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(pspec, P()), out_specs=P(),
                   check=False)
    out = fn(stage_params, xm)
    return out.reshape((b,) + out.shape[2:])


def topology_stages(topology, stage_names):
    """Build the pipeline pieces for a Topology-defined model.

    stage_names: list (one entry per pp rank) of lists of layer names —
    the explicit stage map, the TPU-native form of ParallelNeuralNetwork's
    per-layer `deviceId` pinning (ParallelNeuralNetwork.h:34, config
    `device=` attribute). Constraints (asserted): stages must be
    structurally identical (same layer types + param shapes — GPipe over
    a repeated block), each stage a linear chain whose first layer feeds
    from the previous stage's last (stage 0 from a data layer), and
    stateless (no batch-norm stats inside the body).

    Returns (stage_fn, stack_params, body_names, x_src, body_end):
      stage_fn(slot_params, x) — replays stage 0's layers with
        substituted params (all stages share its structure);
      stack_params(params) — {stage0 param name: [n_stages, ...] stack};
      body_names — every pipelined layer (to skip in the tail forward);
      x_src — the data layer feeding the pipeline;
      body_end — the final stage's last layer name (inject its value).
    """
    from paddle_tpu.core.registry import ApplyContext, get_layer_impl

    by_name = topology.by_name
    n = len(stage_names)
    sigs = []
    for si, st in enumerate(stage_names):
        sig = []
        for li, nm in enumerate(st):
            l = by_name[nm]
            assert not l.states, \
                f"stateful layer {nm!r} unsupported inside a pipeline stage"
            assert l.type != "dropout", \
                f"dropout ({nm!r}) unsupported inside a pipeline stage — " \
                "the stage context has no per-step rng (put dropout in " \
                "the tail, or between body and head)"
            assert len(l.parents) == 1, \
                f"pipeline stages must be linear chains; {nm!r} has " \
                f"{len(l.parents)} inputs"
            expect = st[li - 1] if li > 0 else (
                stage_names[si - 1][-1] if si > 0 else None)
            if expect is not None:
                assert l.parents[0].name == expect, \
                    f"{nm!r} must consume {expect!r}, got " \
                    f"{l.parents[0].name!r}"
            sig.append((l.type,
                        tuple(tuple(ps.shape) for ps in l.params)))
        sigs.append(tuple(sig))
    assert all(s == sigs[0] for s in sigs), \
        "pipeline stages must be structurally identical"
    first = by_name[stage_names[0][0]]
    assert first.parents[0].type == "data", \
        "the pipeline body must start right after a data layer"
    x_src = first.parents[0].name

    name_matrix = [[ps.name for nm in st for ps in by_name[nm].params]
                   for st in stage_names]
    slot_names = name_matrix[0]
    stage0 = [by_name[nm] for nm in stage_names[0]]

    def stage_fn(slot_params, x):
        ctx = ApplyContext("train", None, {})
        prev = x
        for l in stage0:
            impl = get_layer_impl(l.type)
            lp = {ps.name: slot_params[ps.name] for ps in l.params}
            prev = impl["apply"](ctx, l.name, l.config, lp, [prev])
        return prev

    def stack_params(params):
        return {slot_names[j]: jnp.stack(
            [params[name_matrix[i][j]] for i in range(n)])
            for j in range(len(slot_names))}

    body_names = [nm for st in stage_names for nm in st]
    return stage_fn, stack_params, body_names, x_src, stage_names[-1][-1]


def pipeline_loss(stage_fn: Callable, loss_fn: Callable):
    """Compose pipeline + loss into one differentiable objective:
    loss_fn(y, *args) applied to the pipeline output (e.g. softmax CE on
    the last stage's activations)."""
    def objective(stage_params, x, mesh, *loss_args, **kw):
        y = pipeline(stage_fn, stage_params, x, mesh, **kw)
        return loss_fn(y, *loss_args)
    return objective
