"""Pipeline parallelism — GPipe-style microbatch pipelining over the `pp`
mesh axis.

Reference parity: ParallelNeuralNetwork (ParallelNeuralNetwork.h:34-63,
`--parallel_nn`) pinned layers to devices (`deviceId` per layer) and ran
per-device compute threads with async queues between them. TPU-native, the
same capability is a shard_map over `pp`: each chip holds ONE stage's
parameters, activations hop to the next stage via lax.ppermute over ICI,
and a lax.scan over (microbatches + stages - 1) ticks keeps every chip
busy once the pipeline fills (the bubble is the standard (n-1)/(m+n-1)).

Differentiable end-to-end: jax.grad reverses the scan and the ppermutes
into the mirrored backward ring — no hand-written backward schedule.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel._compat import shard_map

from paddle_tpu.parallel.mesh import PP_AXIS


def _microbatch_codec(x, m):
    """Split a boundary pytree into (carried float leaves, static int
    leaves) reshaped to [m, mb, ...].

    The boundary between stages may be a pytree (a SequenceBatch's
    data + lengths): only INEXACT leaves ride the scan carry and the
    ppermute ring — integer leaves (lengths) are identical for every
    stage's output of a given microbatch, so they are closed over and
    re-attached by microbatch index. This keeps integers out of the
    reverse-mode scan/ppermute path entirely.

    Returns (dyn [list of [m, mb, ...] arrays], rebuild(dyn_mb, j),
             collect(dyn_m), b).
    """
    leaves, treedef = jax.tree_util.tree_flatten(x)
    b = leaves[0].shape[0]
    assert b % m == 0, f"microbatches {m} must divide batch {b}"
    mb = b // m
    shaped = [a.reshape((m, mb) + a.shape[1:]) for a in leaves]
    is_dyn = [is_dynamic_leaf(a) for a in leaves]
    dyn = [a for a, d in zip(shaped, is_dyn) if d]
    static = [a for a, d in zip(shaped, is_dyn) if not d]

    def rebuild(dyn_mb, j):
        """Boundary pytree of microbatch j from carried leaves."""
        out = interleave_leaves(dyn_mb, [s[j] for s in static], is_dyn)
        return jax.tree_util.tree_unflatten(treedef, out)

    def collect(dyn_m):
        """Full-batch pytree from [m, mb, ...] carried leaves."""
        out = [a.reshape((b,) + a.shape[2:])
               for a in interleave_leaves(dyn_m, static, is_dyn)]
        return jax.tree_util.tree_unflatten(treedef, out)

    return dyn, rebuild, collect, b


def is_dynamic_leaf(a):
    """THE predicate for what rides the pipeline's scan/ppermute ring
    (and is differentiated): inexact leaves. Integer leaves (lengths)
    are per-microbatch constants. One definition — the codec, the
    strip, and the trainer's prologue vjp all share it."""
    return jnp.issubdtype(a.dtype, jnp.inexact)


def interleave_leaves(dyn, static, is_dyn):
    """Re-zip split leaves back into flat leaf order."""
    di, si, out = 0, 0, []
    for d in is_dyn:
        if d:
            out.append(dyn[di])
            di += 1
        else:
            out.append(static[si])
            si += 1
    return out


def _strip_static(y):
    """The carried form of a stage output: its inexact leaves only."""
    return [a for a in jax.tree_util.tree_leaves(y) if is_dynamic_leaf(a)]


def _assert_boundary_preserving(stage_fn, stage_params, x, m):
    """The codec re-attaches the INPUT boundary's integer leaves to every
    stage's output (rebuild/collect index them by microbatch), which is
    only sound if stage_fn preserves the boundary pytree: same treedef,
    same leaf shapes/dtypes at microbatch size. Checked abstractly once
    per build — a stage that altered lengths or emitted different static
    leaves would otherwise produce a silently wrong output pytree."""
    params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    leaves, treedef = jax.tree_util.tree_flatten(x)
    mb = leaves[0].shape[0] // m
    x_mb = jax.tree_util.tree_unflatten(treedef, [a[:mb] for a in leaves])
    out = jax.eval_shape(stage_fn, params0, x_mb)
    out_flat, out_def = jax.tree_util.tree_flatten(out)
    assert out_def == treedef, (
        f"stage_fn must preserve the boundary pytree structure: "
        f"in {treedef}, out {out_def}")
    in_flat = [a[:mb] for a in leaves]
    for i, (a, o) in enumerate(zip(in_flat, out_flat)):
        assert (o.shape, jnp.dtype(o.dtype)) == \
            (a.shape, jnp.dtype(a.dtype)), (
            f"stage_fn boundary leaf {i} changed "
            f"{a.shape}/{a.dtype} -> {o.shape}/{o.dtype}; the pipeline "
            f"boundary must be shape- and dtype-preserving")


def _tree_where(cond, a, b):
    return jax.tree_util.tree_map(lambda x, y: jnp.where(cond, x, y), a, b)


def pipeline(stage_fn: Callable, stage_params, x, mesh: Mesh,
             num_microbatches: Optional[int] = None,
             axis_name: str = PP_AXIS, remat: bool = False):
    """Run `stage_fn` as an n-stage pipeline.

    stage_fn(params_i, x_mb) -> y_mb, shape-preserving ([mb, ...] in/out;
      a pytree boundary — e.g. a SequenceBatch — is supported, with its
      integer leaves treated as per-microbatch constants).
    stage_params: pytree whose leaves have a leading `n_stages` axis
      (stage i's slice lives on chip i — sharded over `pp`).
    x: [batch, ...] global input; split into `num_microbatches` equal
      microbatches (default: n_stages, the minimum that fills the ring).
    remat: wrap each stage in jax.checkpoint so the backward pass holds
      only stage-BOUNDARY activations per tick and recomputes the stage
      interior — the FLOPs-for-memory trade (identical numerics; the
      standard companion of microbatch pipelining, since scan otherwise
      saves every tick's interior residuals for the reversed pass).

    Returns [batch, ...] outputs (replicated over pp).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == n, \
            f"stage_params leading axis {leaf.shape[0]} != pp={n}"
    m = num_microbatches or n
    _assert_boundary_preserving(stage_fn, stage_params, x, m)
    dyn, rebuild, collect, b = _microbatch_codec(x, m)

    def local(params, *dyn_local):
        # params: stage slice [1, ...] -> squeeze; dyn_local: [m, mb,...]
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        me = lax.axis_index(axis_name)
        ticks = m + n - 1

        state0 = _tree_where(me == 0, [a[0] for a in dyn_local],
                             [jnp.zeros_like(a[0]) for a in dyn_local])
        outbuf0 = [jnp.zeros_like(a) for a in dyn_local]

        def tick(carry, t):
            state, outbuf = carry
            xi_now = jnp.clip(t - me, 0, m - 1)   # this tick's mb index
            y = stage_fn(params, rebuild(state, xi_now))
            yd = _strip_static(y)
            # collect on the last stage: tick t finishes microbatch t-(n-1)
            oi = jnp.clip(t - (n - 1), 0, m - 1)
            take = jnp.logical_and(me == n - 1, t >= n - 1)
            outbuf = [lax.dynamic_update_index_in_dim(
                buf, jnp.where(take, v, buf[oi]), oi, 0)
                for buf, v in zip(outbuf, yd)]
            # hop activations forward one stage
            y_prev = lax.ppermute(yd, axis_name,
                                  [(i, i + 1) for i in range(n - 1)])
            xi = jnp.clip(t + 1, 0, m - 1)
            nxt = _tree_where(me == 0, [a[xi] for a in dyn_local], y_prev)
            return (nxt, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (state0, outbuf0),
                                  jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them
        outbuf = _tree_where(me == n - 1, outbuf,
                             [jnp.zeros_like(a) for a in outbuf])
        return tuple(lax.psum(a, axis_name) for a in outbuf)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(pspec,) + (P(),) * len(dyn), out_specs=P(),
                   check=False)
    out = fn(stage_params, *dyn)
    return collect(list(out))


def topology_stages(topology, stage_names):
    """Build the pipeline pieces for a Topology-defined model.

    stage_names: list (one entry per pp rank) of lists of layer names —
    the explicit stage map, the TPU-native form of ParallelNeuralNetwork's
    per-layer `deviceId` pinning (ParallelNeuralNetwork.h:34, config
    `device=` attribute). Constraints (asserted): stages must be
    structurally identical (same layer types + param shapes + internal
    wiring — GPipe over a repeated block); each stage is a DAG whose
    layers consume either in-stage values or the single BOUNDARY input
    (the previous stage's last layer; stage 0's boundary is a data layer
    OR any computed layer outside the stages — an embedding prologue the
    trainer evaluates first) — residual blocks like a transformer's are
    fine; the stage output is its LAST listed layer; stateless (no
    batch-norm stats inside the body).

    Returns (stage_fn, stack_params, body_names, x_src, body_end):
      stage_fn(slot_params, x) — replays stage 0's DAG with substituted
        params (all stages share its structure);
      stack_params(params) — {stage0 param name: [n_stages, ...] stack};
      body_names — every pipelined layer (to skip in the tail forward);
      x_src — the boundary layer feeding the pipeline (a data layer, or
        a computed prologue layer the trainer forwards first);
      body_end — the final stage's last layer name (inject its value).
    """
    from paddle_tpu.core.registry import ApplyContext, get_layer_impl

    by_name = topology.by_name
    n = len(stage_names)
    sigs = []
    for si, st in enumerate(stage_names):
        boundary = stage_names[si - 1][-1] if si > 0 else None
        in_stage = {nm: k for k, nm in enumerate(st)}
        sig = []
        for li, nm in enumerate(st):
            l = by_name[nm]
            assert not l.states, \
                f"stateful layer {nm!r} unsupported inside a pipeline stage"
            assert l.type != "dropout", \
                f"dropout ({nm!r}) unsupported inside a pipeline stage — " \
                "the stage context has no per-step rng (put dropout in " \
                "the tail, or between body and head)"
            wiring = []
            for p in l.parents:
                if p.name in in_stage:
                    assert in_stage[p.name] < li, \
                        f"{nm!r} consumes {p.name!r} before it is " \
                        "computed — list stage layers in topo order"
                    wiring.append(in_stage[p.name])
                else:
                    if boundary is None:
                        # stage 0's boundary: a data layer, or ANY layer
                        # outside the stages (an embedding prologue the
                        # trainer computes before the pipeline)
                        boundary = p.name
                    assert p.name == boundary, \
                        f"{nm!r} consumes {p.name!r} from outside the " \
                        f"stage; the only allowed external input is the " \
                        f"boundary {boundary!r}"
                    wiring.append(-1)
            sig.append((l.type, tuple(wiring),
                        tuple(tuple(ps.shape) for ps in l.params)))
        sigs.append(tuple(sig))
        if si == 0:
            x_src = boundary
    assert all(s == sigs[0] for s in sigs), \
        "pipeline stages must be structurally identical"
    assert x_src not in {nm for st in stage_names for nm in st}, \
        f"the stage-0 boundary {x_src!r} cannot itself be a stage layer"

    name_matrix = [[ps.name for nm in st for ps in by_name[nm].params]
                   for st in stage_names]
    slot_names = name_matrix[0]
    stage0 = [by_name[nm] for nm in stage_names[0]]
    wiring0 = [w for (_, w, _) in sigs[0]]

    def stage_fn(slot_params, x):
        ctx = ApplyContext("train", None, {})
        vals = []
        for l, wires in zip(stage0, wiring0):
            impl = get_layer_impl(l.type)
            lp = {ps.name: slot_params[ps.name] for ps in l.params}
            ins = [x if w < 0 else vals[w] for w in wires]
            vals.append(impl["apply"](ctx, l.name, l.config, lp, ins))
        return vals[-1]

    def stack_params(params):
        return {slot_names[j]: jnp.stack(
            [params[name_matrix[i][j]] for i in range(n)])
            for j in range(len(slot_names))}

    def unstack(stacked):
        """{global param name: per-stage slice} from a stacked pytree —
        the inverse of stack_params, used to merge per-stage gradients
        back into the flat param-name space (1F1B path)."""
        return {name_matrix[i][j]: stacked[slot_names[j]][i]
                for i in range(n) for j in range(len(slot_names))}

    stack_params.unstack = unstack
    stack_params.param_names = {nm for row in name_matrix for nm in row}
    body_names = [nm for st in stage_names for nm in st]
    return stage_fn, stack_params, body_names, x_src, stage_names[-1][-1]


def pipeline_1f1b(stage_fn: Callable, stage_params, x,
                  tail_vjp: Callable, mesh: Mesh,
                  num_microbatches: Optional[int] = None,
                  axis_name: str = PP_AXIS, tail_args=()):
    """One-forward-one-backward pipeline schedule (PipeDream-flush /
    Megatron 1F1B), hand-scheduled because the backward interleaving
    cannot be expressed through jax.grad of a forward scan.

    stage_fn(params_i, x_mb) -> y_mb, shape-preserving.
    stage_params: pytree with leading [n_stages] axis, sharded over pp.
    tail_vjp(y_mb, j, *tail_args) -> (loss_j, dy_mb, dtail_pytree):
      per-microbatch loss head — called at the LAST stage the moment
      microbatch j's forward completes, so its cotangent enters the
      backward ring in the same tick (the defining property of 1F1B).
    tail_args: pytrees the tail differentiates (params, feed slices) —
      threaded through the shard_map as replicated operands rather than
      captured in the closure, because cotangents of closure-captured
      committed arrays carry their Auto-mesh shardings into the Manual
      context and fail sharding-in-types checks.

    Returns (loss_sum, y [batch, ...], stage_grads stacked like
    stage_params, dtail_sum, dx) — dx is the cotangent of x's float
    leaves ([batch, ...] list), i.e. the PROLOGUE gradient when the
    pipeline input was computed by earlier layers (embeddings).

    Schedule: microbatch j runs forward at stage s on tick j+s and
    backward on tick j + 2(n-1) - s; one scan over m + 2(n-1) ticks
    carries a RING BUFFER of 2n-1 saved stage INPUTS (backward
    recomputes the stage from its input, vjp'd immediately — residuals
    never outlive a tick). Peak activation state is therefore O(n
    stages), independent of the microbatch count m, where the
    jax.grad-reversed GPipe scan must carry O(m + n) tick states: the
    memory-for-schedule trade that lets m grow (and the bubble
    (n-1)/(m+n-1) shrink) without OOM. Under SPMD every rank executes
    every tick's masked F and B slots, so at small m the extra n-1
    drain ticks cost wall-clock vs GPipe; the ratio (m+2n-2)/(m+n-1)
    approaches 1 in exactly the large-m regime 1F1B exists for. The
    TAIL, however, is not masked-redundant: it runs under a real
    per-device lax.cond, so a vocab-sized LM head executes exactly m
    times on the last rank — not n*(m+2n-2) times everywhere.
    Reference analogue: ParallelNeuralNetwork's per-device compute
    threads with async queues (ParallelNeuralNetwork.h:34), modernized.
    """
    n = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == n, \
            f"stage_params leading axis {leaf.shape[0]} != pp={n}"
    m = num_microbatches or n
    _assert_boundary_preserving(stage_fn, stage_params, x, m)
    dyn, rebuild, collect, b = _microbatch_codec(x, m)
    ring = 2 * n - 1

    def local(params, targs, *dyn_local):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        me = lax.axis_index(axis_name)

        def stage_dyn(p, d, j):
            """Stage over CARRIED (float) leaves only: statics attach by
            microbatch index via the closure, so vjp cotangents stay
            float (no float0 riding the ring)."""
            return _strip_static(stage_fn(p, rebuild(list(d), j)))

        zero_mb = [jnp.zeros_like(a[0]) for a in dyn_local]

        # probe shapes for the accumulators (abstract eval only)
        y_shapes = jax.eval_shape(stage_dyn, params, tuple(zero_mb),
                                  jnp.int32(0))
        zero_y = [jnp.zeros(s.shape, s.dtype) for s in y_shapes]
        loss_probe, dy_probe, dtail_probe = jax.eval_shape(
            lambda y, ta: tail_vjp(rebuild(y, jnp.int32(0)), jnp.int32(0),
                                   *ta), list(zero_y), targs)
        g_zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        dtail_zero = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), dtail_probe)

        del zero_y  # (probe only)
        carry0 = (zero_mb,                       # x_state: incoming act
                  [jnp.zeros(s.shape, s.dtype)
                   for s in _strip_static(dy_probe)],     # dy_state
                  [jnp.zeros((ring,) + a.shape, a.dtype) for a in zero_mb],
                  [jnp.zeros((m, ) + s.shape, s.dtype) for s in y_shapes],
                  [jnp.zeros((m, ) + a.shape, a.dtype) for a in zero_mb],
                  g_zero, dtail_zero, jnp.float32(0.0))

        def tick(carry, t):
            x_state, dy_state, inbuf, youtbuf, dxbuf, g_acc, dtail_acc, \
                loss_acc = carry
            # ---- forward slot: mb fj = t - me
            fj = t - me
            f_active = jnp.logical_and(fj >= 0, fj < m)
            fjc = jnp.clip(fj, 0, m - 1)
            x_in = _tree_where(me == 0, [a[fjc] for a in dyn_local],
                               x_state)
            y = stage_dyn(params, tuple(x_in), fjc)
            slot_f = fjc % ring
            inbuf = [lax.dynamic_update_index_in_dim(
                buf, jnp.where(f_active, v, buf[slot_f]), slot_f, 0)
                for buf, v in zip(inbuf, x_in)]
            last = me == n - 1
            take_y = jnp.logical_and(last, f_active)
            youtbuf = [lax.dynamic_update_index_in_dim(
                buf, jnp.where(take_y, v, buf[fjc]), fjc, 0)
                for buf, v in zip(youtbuf, y)]
            # ---- tail head: lives on the last stage only. Under manual
            # SPMD lax.cond is a real per-device conditional, so the
            # (potentially vocab-sized) head fwd+bwd runs ONLY on rank
            # n-1's m active ticks — not n*(m+2n-2) times masked, which
            # for a big-vocab LM tail would dwarf the 1F1B win.
            def _tail_live(op):
                y_, j_ = op
                l_, dy_t_, dt_ = tail_vjp(rebuild(list(y_), j_), j_,
                                          *targs)
                return (jnp.asarray(l_, loss_probe.dtype),
                        _strip_static(dy_t_), dt_)

            def _tail_skip(op):
                return (jnp.zeros(loss_probe.shape, loss_probe.dtype),
                        [jnp.zeros(s.shape, s.dtype)
                         for s in _strip_static(dy_probe)],
                        dtail_zero)

            loss_j, dy_tail, dtail_j = lax.cond(
                take_y, _tail_live, _tail_skip, (list(y), fjc))
            # cond's skip branch returns exact zeros — no re-mask needed
            loss_acc = loss_acc + loss_j
            dtail_acc = jax.tree_util.tree_map(
                lambda a, d: a + d, dtail_acc, dtail_j)
            # ---- backward slot: mb bj = t - 2(n-1) + me
            bj = t - 2 * (n - 1) + me
            b_active = jnp.logical_and(bj >= 0, bj < m)
            bjc = jnp.clip(bj, 0, m - 1)
            dy_in = _tree_where(last, dy_tail, dy_state)
            x_saved = tuple(buf[bjc % ring] for buf in inbuf)
            _, svjp = jax.vjp(
                lambda p, d: stage_dyn(p, d, bjc), params, x_saved)
            dp_j, dx_j = svjp(dy_in)
            g_acc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(b_active, d, jnp.zeros_like(d)),
                g_acc, dp_j)
            # stage 0's dx is the PROLOGUE's cotangent (embeddings etc.
            # computed before the pipeline): collect it per microbatch
            take_dx = jnp.logical_and(me == 0, b_active)
            dxbuf = [lax.dynamic_update_index_in_dim(
                buf, jnp.where(take_dx, v, buf[bjc]), bjc, 0)
                for buf, v in zip(dxbuf, dx_j)]
            # ---- hop: activations up, cotangents down
            y_prev = lax.ppermute(y, axis_name,
                                  [(i, i + 1) for i in range(n - 1)])
            dx_next = lax.ppermute(list(dx_j), axis_name,
                                   [(i, i - 1) for i in range(1, n)])
            return (y_prev, dx_next, inbuf, youtbuf, dxbuf, g_acc,
                    dtail_acc, loss_acc), None

        (x_s, dy_s, inbuf, youtbuf, dxbuf, g_acc, dtail_acc,
         loss_acc), _ = \
            lax.scan(tick, carry0, jnp.arange(m + 2 * (n - 1)))
        youtbuf = _tree_where(me == n - 1, youtbuf,
                              [jnp.zeros_like(a) for a in youtbuf])
        youtbuf = [lax.psum(a, axis_name) for a in youtbuf]
        dxbuf = _tree_where(me == 0, dxbuf,
                            [jnp.zeros_like(a) for a in dxbuf])
        dxbuf = [lax.psum(a, axis_name) for a in dxbuf]
        loss_sum = lax.psum(jnp.where(me == n - 1, loss_acc, 0.0),
                            axis_name)
        dtail = jax.tree_util.tree_map(
            lambda d: lax.psum(jnp.where(me == n - 1, d,
                                         jnp.zeros_like(d)), axis_name),
            dtail_acc)
        g_out = jax.tree_util.tree_map(lambda g: g[None], g_acc)
        return loss_sum, tuple(youtbuf), g_out, dtail, tuple(dxbuf)

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    gspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(pspec, P()) + (P(),) * len(dyn),
                   out_specs=(P(), P(), gspec, P(), P()),
                   check=False)
    loss_sum, ym, g_stacked, dtail, dxm = fn(stage_params,
                                             tuple(tail_args), *dyn)
    # dx leaves flattened back to [batch, ...] (the prologue cotangent)
    dx = [a.reshape((b,) + a.shape[2:]) for a in dxm]
    return (loss_sum, collect(list(ym)), g_stacked, dtail, dx)


def pipeline_loss(stage_fn: Callable, loss_fn: Callable):
    """Compose pipeline + loss into one differentiable objective:
    loss_fn(y, *args) applied to the pipeline output (e.g. softmax CE on
    the last stage's activations)."""
    def objective(stage_params, x, mesh, *loss_args, **kw):
        y = pipeline(stage_fn, stage_params, x, mesh, **kw)
        return loss_fn(y, *loss_args)
    return objective
