"""Image preprocessing utilities — python/paddle/v2/image.py parity.

Pure-numpy implementations (the reference shells out to cv2; PIL/cv2 stay
optional here so the loaders work in minimal containers): resize_short,
center/random crop, flip, CHW conversion, and the simple_transform /
load_and_transform pipelines the image demos feed through.
"""

from __future__ import annotations

import numpy as np


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image buffer to HWC uint8 (needs PIL)."""
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(data))
    im = im.convert("RGB" if is_color else "L")
    arr = np.asarray(im)
    return arr if is_color else arr[..., None]


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize_bilinear(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize in numpy (HWC)."""
    ih, iw = im.shape[:2]
    if (ih, iw) == (h, w):
        return im
    ys = np.linspace(0, ih - 1, h)
    xs = np.linspace(0, iw - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    im = im.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORT side equals `size` (image.py:143)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _resize_bilinear(im, nh, nw)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (the framework's flat channel-major feed layout)."""
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    hs = max((h - size) // 2, 0)
    ws = max((w - size) // 2, 0)
    return im[hs:hs + size, ws:ws + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    hs = rng.randint(0, max(h - size, 0) + 1)
    ws = rng.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean=None, rng=None) -> np.ndarray:
    """resize-short -> crop (random+flip when training, center otherwise)
    -> CHW float32 -> optional mean subtraction (image.py:265)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean.reshape((-1,) + (1,) * (im.ndim - 1)) if mean.ndim == 1 \
            else mean
    return im


def load_and_transform(path: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None) -> np.ndarray:
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
