"""Image preprocessing utilities — python/paddle/v2/image.py parity.

Pure-numpy implementations (the reference shells out to cv2; PIL/cv2 stay
optional here so the loaders work in minimal containers): resize_short,
center/random crop, flip, CHW conversion, and the simple_transform /
load_and_transform pipelines the image demos feed through.
"""

from __future__ import annotations

import numpy as np


def batch_images_from_tar(data_file: str, dataset_name: str, img2label,
                          num_per_batch: int = 1024) -> str:
    """Read images out of a tar archive and shard them into pickled batch
    files of `num_per_batch` samples each, plus a meta file listing the
    shard paths — the flowers-scale ingestion path
    (python/paddle/v2/image.py:33). Returns the meta-file path. Each shard
    is a pickle of {"label": [...], "data": [raw image bytes, ...]}."""
    import os
    import pickle
    import tarfile

    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, dataset_name + ".txt")
    # out_path appears only via the final rename below, so its existence
    # certifies a COMPLETE ingestion — a crash mid-run leaves only the
    # .tmp workdir, and the rerun redoes the work instead of silently
    # serving a partial shard set
    if os.path.exists(out_path):
        return meta_file
    work = out_path + ".tmp"
    if os.path.exists(work):
        import shutil
        shutil.rmtree(work)
    os.makedirs(work)

    data, labels, file_id = [], [], 0

    def _flush():
        nonlocal file_id, data, labels
        with open(os.path.join(work, f"batch_{file_id}"), "wb") as f:
            pickle.dump({"label": labels, "data": data}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        file_id += 1
        data, labels = [], []

    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name in img2label:
                data.append(tf.extractfile(mem).read())
                labels.append(img2label[mem.name])
                if len(data) == num_per_batch:
                    _flush()
    if data:
        _flush()

    with open(meta_file + ".tmp", "w") as meta:
        for i in range(file_id):
            meta.write(os.path.abspath(
                os.path.join(out_path, f"batch_{i}")) + "\n")
    # meta first: if we crash between the two renames, out_path is still
    # absent, so the rerun redoes the work and rewrites the meta
    os.replace(meta_file + ".tmp", meta_file)
    os.rename(work, out_path)
    return meta_file


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image buffer to HWC uint8 (needs PIL)."""
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(data))
    im = im.convert("RGB" if is_color else "L")
    arr = np.asarray(im)
    return arr if is_color else arr[..., None]


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize_bilinear(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize in numpy (HWC)."""
    ih, iw = im.shape[:2]
    if (ih, iw) == (h, w):
        return im
    ys = np.linspace(0, ih - 1, h)
    xs = np.linspace(0, iw - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    im = im.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORT side equals `size` (image.py:143)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _resize_bilinear(im, nh, nw)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (the framework's flat channel-major feed layout)."""
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    hs = max((h - size) // 2, 0)
    ws = max((w - size) // 2, 0)
    return im[hs:hs + size, ws:ws + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    hs = rng.randint(0, max(h - size, 0) + 1)
    ws = rng.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean=None, rng=None) -> np.ndarray:
    """resize-short -> crop (random+flip when training, center otherwise)
    -> CHW float32 -> optional mean subtraction (image.py:265)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean.reshape((-1,) + (1,) * (im.ndim - 1)) if mean.ndim == 1 \
            else mean
    return im


def load_and_transform(path: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None) -> np.ndarray:
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
