#!/usr/bin/env python
"""ptlint entry point — `python tools/ptlint.py [paths...]`.

Thin wrapper over paddle_tpu.analysis.runner.main so the linter runs
without installing the package's console script. CI uses
``--format=github`` to render findings as inline PR annotations; see
docs/static_analysis.md for the rule catalogue, suppression syntax and
the baseline workflow. tests/test_lint.py runs the same analysis as a
tier-1 gate.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    # default the root to the repo this script lives in, so the
    # pyproject config + baseline resolve regardless of the cwd
    argv = sys.argv[1:]
    if not any(a.startswith("--root") for a in argv):
        argv = ["--root", os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))] + argv
    sys.exit(main(argv))
