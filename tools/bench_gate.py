#!/usr/bin/env python
"""bench_gate — the perf regression gate over bench.py's smoke tier.

The BENCH_r0x trajectory existed but nothing enforced it: a perf
regression could land silently. This tool compares a smoke-tier result
(``python bench.py --suite smoke`` or ``--run`` here) against the
committed ``BENCH_SMOKE_BASELINE.json`` with PER-METRIC tolerances and
fails the build on regression — wired into tier-1 by
tests/test_bench_gate.py so every later scale/speed PR lands with its
guard (ROADMAP item 5; docs/observability.md "The perf gate").

Baseline schema (v1)::

    {"v": 1, "rows": {"train_tiny": {
        "step_compiles":      {"value": 3,   "kind": "count",
                               "max_slack": 3},
        "steps_per_s":        {"value": 1300, "kind": "rate",
                               "min_ratio": 0.02},
        "p50_ms":             {"value": 0.1, "kind": "latency",
                               "max_ratio": 20, "abs_floor_ms": 50},
        "served":             {"value": 17,  "kind": "info"}}}}

Metric kinds:
  count    lower-is-better integer-ish (compiles, host syncs/step):
           FAIL when current > value + max_slack. The tight tier —
           deterministic on any machine.
  rate     higher-is-better throughput: FAIL when
           current < value * min_ratio. Loose: catches
           order-of-magnitude collapses, not noise.
  latency  lower-is-better milliseconds: FAIL when
           current > max(value * max_ratio, abs_floor_ms).
  info     recorded, never gated.

Output formats text/github/json mirror ptlint; ``--write-baseline``
regenerates the baseline from a current run while PRESERVING each
metric's kind/tolerance fields (re-baselining intentionally is a
one-command workflow; see docs/observability.md for when that is
legitimate). Exit codes: 0 clean, 1 regression/missing metric/stale
baseline row, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

DEFAULT_BASELINE = "BENCH_SMOKE_BASELINE.json"

#: tolerance defaults per kind, used when a baseline entry (or
#: --write-baseline) does not spell its own out
KIND_DEFAULTS = {
    "count": {"max_slack": 3},
    "rate": {"min_ratio": 0.02},
    "latency": {"max_ratio": 20.0, "abs_floor_ms": 50.0},
    "info": {},
}


def classify(metric: str) -> str:
    """Default kind for a metric name (used by --write-baseline when
    the previous baseline has no entry to inherit from)."""
    if "compiles" in metric or metric.startswith("host_syncs"):
        return "count"
    if metric.endswith("_per_s"):
        return "rate"
    if metric.endswith("_ms"):
        return "latency"
    return "info"


@dataclass
class GateCheck:
    row: str
    metric: str
    kind: str
    baseline: Optional[float]
    current: Optional[float]
    limit: Optional[float]
    ok: bool
    message: str

    @property
    def name(self) -> str:
        return f"{self.row}.{self.metric}"


@dataclass
class GateResult:
    checks: List[GateCheck] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def failures(self) -> List[GateCheck]:
        return [c for c in self.checks if not c.ok]

    @property
    def ok(self) -> bool:
        return not self.failures


def _rows(blob: dict) -> Dict[str, dict]:
    if not isinstance(blob, dict) or "rows" not in blob:
        raise ValueError("expected {'v': 1, 'rows': {...}}")
    return blob["rows"]


def compare(results: dict, baseline: dict) -> GateResult:
    """Every baseline metric must be present and within tolerance in
    ``results``; metrics present only in results are noted (uncovered),
    not failed."""
    res = GateResult()
    brows, rrows = _rows(baseline), _rows(results)
    for row_name in sorted(brows):
        brow = brows[row_name]
        rrow = rrows.get(row_name)
        for metric in sorted(brow):
            spec = brow[metric]
            if not isinstance(spec, dict) or "value" not in spec:
                continue                    # comment / free-form field
            kind = spec.get("kind", classify(metric))
            base_val = spec["value"]
            if rrow is None or metric not in rrow:
                res.checks.append(GateCheck(
                    row_name, metric, kind, base_val, None, None, False,
                    "metric missing from the current run — the smoke "
                    "tier lost coverage (or the row failed to run)"))
                continue
            cur = float(rrow[metric])
            tol = {**KIND_DEFAULTS.get(kind, {}), **spec}
            if kind == "count":
                limit = float(base_val) + float(tol["max_slack"])
                ok = cur <= limit
                msg = (f"{cur:g} <= {limit:g} "
                       f"(baseline {base_val:g} + slack)") if ok else (
                    f"{cur:g} exceeds {limit:g} (baseline "
                    f"{base_val:g} + slack {tol['max_slack']:g}) — a "
                    "count that scales with the step count means the "
                    "hot path regressed (recompiles / extra host "
                    "syncs)")
            elif kind == "rate":
                limit = float(base_val) * float(tol["min_ratio"])
                ok = cur >= limit
                msg = (f"{cur:g} >= floor {limit:g}") if ok else (
                    f"{cur:g} below floor {limit:g} "
                    f"({tol['min_ratio']:g}x of baseline "
                    f"{base_val:g}) — throughput collapsed")
            elif kind == "latency":
                limit = max(float(base_val) * float(tol["max_ratio"]),
                            float(tol["abs_floor_ms"]))
                ok = cur <= limit
                msg = (f"{cur:g} <= ceiling {limit:g}") if ok else (
                    f"{cur:g} above ceiling {limit:g} "
                    f"({tol['max_ratio']:g}x of baseline "
                    f"{base_val:g} ms) — latency exploded")
            else:                            # info: recorded only
                limit, ok = None, True
                msg = f"recorded {cur:g} (not gated)"
            res.checks.append(GateCheck(row_name, metric, kind,
                                        float(base_val), cur, limit,
                                        ok, msg))
        if rrow:
            for metric in sorted(set(rrow) - set(brow)):
                res.notes.append(
                    f"{row_name}.{metric}: present in the run but not "
                    "in the baseline — re-baseline to start gating it")
    for row_name in sorted(set(rrows) - set(brows)):
        res.notes.append(f"row {row_name!r}: not in the baseline — "
                         "re-baseline to start gating it")
    return res


def write_baseline(path: str, results: dict,
                   prev: Optional[dict] = None) -> dict:
    """Regenerate the baseline from ``results``, inheriting each
    metric's kind/tolerance fields from ``prev`` when present."""
    prev_rows = _rows(prev) if prev else {}
    rows: Dict[str, dict] = {}
    for row_name, rrow in sorted(_rows(results).items()):
        out_row: Dict[str, dict] = {}
        for metric, val in sorted(rrow.items()):
            if not isinstance(val, (int, float)) or \
                    isinstance(val, bool):
                continue
            old = prev_rows.get(row_name, {}).get(metric, {})
            kind = old.get("kind", classify(metric))
            entry = {"value": val, "kind": kind}
            for k, dflt in KIND_DEFAULTS.get(kind, {}).items():
                entry[k] = old.get(k, dflt)
            out_row[metric] = entry
        rows[row_name] = out_row
    blob = {
        "v": 1,
        "_note": "perf-gate smoke baseline — regenerate DELIBERATELY "
                 "with `python tools/bench_gate.py --run "
                 "--write-baseline` and justify the re-baseline in the "
                 "PR (docs/observability.md)",
        "rows": rows,
    }
    with open(path, "w") as f:
        json.dump(blob, f, indent=2, sort_keys=True)
        f.write("\n")
    return blob


# ------------------------------------------------------------------ output
def format_gate(res: GateResult, fmt: str = "text") -> str:
    lines: List[str] = []
    if fmt == "github":
        for c in res.failures:
            lines.append(f"::error::bench_gate {c.name}: {c.message}")
        for n in res.notes:
            lines.append(f"::notice::bench_gate: {n}")
    elif fmt == "json":
        lines.append(json.dumps({
            "ok": res.ok,
            "checks": [c.__dict__ for c in res.checks],
            "failures": [c.name for c in res.failures],
            "notes": res.notes}, indent=2))
    else:
        for c in res.checks:
            mark = "ok  " if c.ok else "FAIL"
            lines.append(f"{mark} {c.name} [{c.kind}]: {c.message}")
        for n in res.notes:
            lines.append(f"note {n}")
        lines.append(
            f"bench_gate: {len(res.checks)} metric(s) checked, "
            f"{len(res.failures)} regression(s)")
    return "\n".join(lines)


def _run_smoke() -> dict:
    """Import bench.py from the repo root (this file lives in tools/)
    and run the smoke tier in-process."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench
    return bench.bench_smoke()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_gate",
        description="perf regression gate over the bench.py smoke "
                    "tier (docs/observability.md)")
    ap.add_argument("--results", default=None,
                    help="smoke-result JSON file (bench.py --suite "
                         "smoke --out ...)")
    ap.add_argument("--run", action="store_true",
                    help="run the smoke tier in-process instead of "
                         "reading --results")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline path (default {DEFAULT_BASELINE})")
    ap.add_argument("--format", default="text",
                    choices=["text", "github", "json"])
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run "
                         "(keeps existing per-metric tolerances)")
    args = ap.parse_args(argv)

    try:
        if args.run:
            results = _run_smoke()
        elif args.results:
            with open(args.results) as f:
                results = json.load(f)
        else:
            print("bench_gate: need --results FILE or --run",
                  file=sys.stderr)
            return 2
        prev = None
        if os.path.exists(args.baseline):
            with open(args.baseline) as f:
                prev = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.baseline, results, prev)
        print(f"bench_gate: wrote baseline to {args.baseline}")
        return 0

    if prev is None:
        print(f"bench_gate: no baseline at {args.baseline} — create "
              "one with --write-baseline", file=sys.stderr)
        return 2
    res = compare(results, prev)
    out = format_gate(res, args.format)
    if out:
        print(out)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
