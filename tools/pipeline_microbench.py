"""GPipe vs 1F1B microbench: peak temp memory + step time vs microbatch
count, on the virtual CPU pp-mesh (run: python tools/pipeline_microbench.py).

The point being measured: the jax.grad-reversed GPipe scan carries
O(m + S) tick states through the backward, so its temp footprint grows
with the microbatch count m; the hand-scheduled 1F1B ring holds O(S)
stage inputs regardless of m. Throughput at small m favors GPipe (the
1F1B timeline is m + 2S - 2 ticks vs m + S - 1, and SPMD pays every
masked slot); the ratio approaches 1 as m grows — which is exactly the
regime the O(S) memory enables. Numbers land in docs/parallelism.md.
"""

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel import create_mesh
from paddle_tpu.parallel.mesh import PP_AXIS
from paddle_tpu.parallel.pipeline import pipeline, pipeline_1f1b

S, D, MB = 4, 256, 16


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"])


def build(mesh, m, schedule):
    sp = {"w": jnp.stack([jnp.eye(D) * 0.9 for _ in range(S)])}
    x = jnp.asarray(np.random.RandomState(0).randn(m * MB, D), jnp.float32)

    if schedule == "gpipe":
        def loss(sp, x):
            y = pipeline(stage_fn, sp, x, mesh, num_microbatches=m,
                         remat=True)
            return jnp.sum(y * y)
        fn = jax.jit(jax.grad(loss))
    else:
        def tail_vjp(y_mb, j):
            loss_j, vjp = jax.vjp(lambda y: jnp.sum(y * y), y_mb)
            (dy,) = vjp(jnp.float32(1.0))
            return loss_j, dy, {}

        def grads(sp, x):
            _, _, g, _, _ = pipeline_1f1b(stage_fn, sp, x, tail_vjp, mesh,
                                          num_microbatches=m)
            return g
        fn = jax.jit(grads)

    compiled = fn.lower(sp, x).compile()
    mem = compiled.memory_analysis()
    temp = getattr(mem, "temp_size_in_bytes", None)
    out = compiled(sp, x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(10):
        out = compiled(sp, x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / 10 * 1e3
    return temp, dt


def main():
    mesh = create_mesh([(PP_AXIS, S)])
    print(f"{'m':>4} {'gpipe temp MB':>14} {'1f1b temp MB':>13} "
          f"{'gpipe ms':>9} {'1f1b ms':>8}")
    for m in (4, 8, 16, 32, 64):
        tg, dg = build(mesh, m, "gpipe")
        t1, d1 = build(mesh, m, "1f1b")
        print(f"{m:>4} {tg / 1e6:>14.2f} {t1 / 1e6:>13.2f} "
              f"{dg:>9.2f} {d1:>8.2f}")


if __name__ == "__main__":
    main()
