#!/usr/bin/env python
"""Plot the training cost curve from trainer logs
(python/paddle/utils/plotcurve.py).

The reference greps ``AvgCost=...`` out of `paddle train` stdout and plots
passes x cost with matplotlib. The CLI here prints
``Pass P, Batch B, Cost C, ...`` lines (cli.py _job_train) and demo scripts
print ``pass P ... cost=C``; both forms are parsed. Usage:

    python tools/plotcurve.py [-o curve.png] [--csv curve.csv] [log ...]

Reads stdin when no log file is given, exactly like the reference
(plotcurve.py: "cat train.log | python plotcurve.py"). Without matplotlib
(not in the TPU image) it falls back to --csv / stdout so the data is
still usable.
"""

import argparse
import re
import sys

# "Pass 3, Batch 120, Cost 0.482911, ..." (cli) / "... cost=0.4829 ..." (demos)
_PAT = re.compile(
    r"[Pp]ass\s+(\d+).*?(?:Cost\s+|cost=)([0-9]+(?:\.[0-9]+)?(?:[eE][+-]?\d+)?)")


def parse(lines):
    """-> list of (pass_id, cost) in log order."""
    points = []
    for line in lines:
        m = _PAT.search(line)
        if m:
            points.append((int(m.group(1)), float(m.group(2))))
    return points


def per_pass_avg(points):
    sums = {}
    for p, c in points:
        sums.setdefault(p, []).append(c)
    return sorted((p, sum(cs) / len(cs)) for p, cs in sums.items())


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logs", nargs="*", help="log files (default: stdin)")
    ap.add_argument("-o", "--output", help="output image (needs matplotlib)")
    ap.add_argument("--csv", help="write pass,avg_cost CSV here")
    args = ap.parse_args(argv)

    lines = []
    if args.logs:
        for path in args.logs:
            with open(path) as f:
                lines.extend(f)
    else:
        lines = sys.stdin.readlines()

    points = parse(lines)
    if not points:
        print("no cost lines found", file=sys.stderr)
        return 1
    curve = per_pass_avg(points)

    if args.csv:
        with open(args.csv, "w") as f:
            f.write("pass,avg_cost\n")
            for p, c in curve:
                f.write(f"{p},{c:.6f}\n")
    if args.output:
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            print("matplotlib unavailable; use --csv instead",
                  file=sys.stderr)
            return 1
        xs, ys = zip(*curve)
        plt.plot(xs, ys, marker="o")
        plt.xlabel("pass")
        plt.ylabel("avg cost")
        plt.savefig(args.output)
    if not args.output and not args.csv:
        for p, c in curve:
            print(f"pass {p}: avg cost {c:.6f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
