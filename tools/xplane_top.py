"""Summarize a JAX TPU .xplane.pb trace: top HLO ops by self time.

Usage: python tools/xplane_top.py /tmp/jax_trace [n]
Part of the profiling loop (utils/stats.py Stat.h parity bridges Python
scopes into these traces; this reads the device side back out).
"""

import collections
import glob
import sys

from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E501  (TF bundles the TSL xplane schema)


def load(path):
    xs = sorted(glob.glob(f"{path}/**/*.xplane.pb", recursive=True))
    assert xs, f"no xplane under {path}"
    sp = xplane_pb2.XSpace()
    with open(xs[-1], "rb") as f:
        sp.ParseFromString(f.read())
    return sp


def top_ops(sp, n=25):
    """Aggregate XLA op self-times on the TPU device plane."""
    rows = []
    for p in sp.planes:
        if "TPU" not in p.name and "/device" not in p.name.lower():
            continue
        ev_meta = {m.id: m for m in p.event_metadata.values()}
        st_meta = {m.id: m.name for m in p.stat_metadata.values()}
        for line in p.lines:
            if line.name not in ("XLA Ops", "Steps"):
                continue
            agg = collections.defaultdict(lambda: [0.0, 0])
            for e in line.events:
                md = ev_meta.get(e.metadata_id)
                name = md.name if md else str(e.metadata_id)
                cat = ""
                for s in list(md.stats if md else []) + list(e.stats):
                    if st_meta.get(s.metadata_id) == "hlo_category":
                        cat = s.str_value or s.ref_value
                key = (name, cat)
                agg[key][0] += e.duration_ps / 1e9   # -> ms
                agg[key][1] += 1
            total = sum(v[0] for v in agg.values())
            rows.append((p.name, line.name, total, agg))
    for pname, lname, total, agg in rows:
        print(f"\n== {pname} / {lname}: total {total:.3f} ms")
        by_cat = collections.defaultdict(float)
        for (nm, cat), (ms, cnt) in agg.items():
            by_cat[cat or "?"] += ms
        print("-- by category:")
        for cat, ms in sorted(by_cat.items(), key=lambda x: -x[1]):
            print(f"   {cat:<30} {ms:9.3f} ms  {100*ms/max(total,1e-9):5.1f}%")
        print("-- top ops:")
        for (nm, cat), (ms, cnt) in sorted(agg.items(),
                                           key=lambda x: -x[1][0])[:n]:
            print(f"   {ms:9.3f} ms x{cnt:<4} [{cat:<18}] {nm[:90]}")


if __name__ == "__main__":
    sp = load(sys.argv[1] if len(sys.argv) > 1 else "/tmp/jax_trace")
    top_ops(sp, int(sys.argv[2]) if len(sys.argv) > 2 else 25)
