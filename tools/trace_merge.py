#!/usr/bin/env python
"""trace_merge — fuse N per-host journals + chrome traces into one
timeline (the standalone twin of `paddle_tpu trace merge`).

    python tools/trace_merge.py --journal w0.jsonl w1.jsonl \
        --trace w0_trace.json w1_trace.json \
        --out-journal merged.jsonl --out-trace merged.json

Clock skew between hosts is adjusted from each journal's `clock_sync`
record (emitted by trainer/coordinator.sync_clock over the coordinator
heartbeat channel) or an explicit `--offset host=SECONDS`. See
docs/observability.md "Trace context & postmortems" and
paddle_tpu/obs/merge.py for the logic.
"""

import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from paddle_tpu.obs.merge import main
    sys.exit(main())
