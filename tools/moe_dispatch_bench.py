"""einsum vs sort MoE dispatch on device — the ops/moe.py crossover.

Forward+backward step time for moe_ffn under both dispatch modes across
single-host token counts; slope timing (T_2N - T_N over chained steps)
cancels dispatch/readback constants. Run on an IDLE host.

    python tools/moe_dispatch_bench.py [--dtype bfloat16]
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import moe as moe_ops


def bench(mode, n, d, f, E, k, dtype, reps=5):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, d), dtype)
    gate_w = jnp.asarray(rng.randn(d, E), jnp.float32)
    w_up = jnp.asarray(0.1 * rng.randn(E, d, f), dtype)
    w_down = jnp.asarray(0.1 * rng.randn(E, f, d), dtype)

    def loss(gw, wu, wd):
        y, aux = moe_ops.moe_ffn(x, None, gw, wu, wd, k=k,
                                 dispatch_mode=mode)
        return (jnp.sum(y.astype(jnp.float32) ** 2) +
                0.01 * aux).astype(jnp.float32)

    grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def chain(steps):
        gw = gate_w
        for _ in range(steps):
            g = grad(gw, w_up, w_down)
            gw = gw - 1e-6 * g[0]
        jax.block_until_ready(gw)

    chain(2)  # compile + warm
    best = []
    for _ in range(reps):
        t0 = time.perf_counter(); chain(4); t1 = time.perf_counter()
        chain(8)
        t2 = time.perf_counter()
        best.append((t2 - t1 - (t1 - t0)) / 4 * 1e3)
    best.sort()
    return best[len(best) // 2], best[0], best[-1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--d", type=int, default=512)
    ap.add_argument("--f", type=int, default=2048)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--k", type=int, default=2)
    args = ap.parse_args()
    dtype = jnp.dtype(args.dtype)
    print(f"device={jax.devices()[0].device_kind} dtype={args.dtype} "
          f"d={args.d} f={args.f} E={args.experts} k={args.k}")
    for n in (8192, 32768, 131072, 262144):
        row = {}
        for mode in ("einsum", "sort"):
            try:
                med, lo, hi = bench(mode, n, args.d, args.f,
                                    args.experts, args.k, dtype)
                row[mode] = (med, lo, hi)
            except Exception as e:   # OOM at large n for einsum
                row[mode] = e
        for mode, v in row.items():
            if isinstance(v, tuple):
                print(f"n={n:7d} {mode:6s} {v[0]:8.2f} ms "
                      f"[{v[1]:.2f}, {v[2]:.2f}]")
            else:
                print(f"n={n:7d} {mode:6s} FAILED: "
                      f"{type(v).__name__}: {str(v)[:120]}")


if __name__ == "__main__":
    main()
